"""dataset_tokenizer CLI + TokenizedDataset tests.

The C++ packer is exercised through its real CLI surface; BPE output is
checked against the HuggingFace ``tokenizers`` implementation configured
with the same vocab/merges (dataset-parity goal, SURVEY.md §7 hard part 4).
"""

import json
import os
import subprocess

import numpy as np
import pytest

from kubernetes_cloud_tpu.data import TokenizedDataset, build_tokenizer, run_tokenizer


@pytest.fixture(scope="module")
def binary():
    return build_tokenizer()


def write_docs(tmp_path, docs):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    for i, text in enumerate(docs):
        (d / f"{i:03d}.txt").write_text(text)
    return str(d)


def test_byte_packing_exact(tmp_path, binary):
    docs = ["abc", "defgh"]
    out = str(tmp_path / "out.tokens")
    run_tokenizer([
        "--input", write_docs(tmp_path, docs), "--output", out,
        "--tokenizer", "byte", "--context-size", "4",
        "--eot-token", "0", "--pad-token", "1",
    ], binary=binary)
    tokens = np.fromfile(out, dtype=np.uint16).reshape(-1, 4)
    # stream: a b c EOT | d e f g | h EOT pad pad
    expect = np.array([
        [97, 98, 99, 0],
        [100, 101, 102, 103],
        [104, 0, 1, 1],
    ], np.uint16)
    np.testing.assert_array_equal(tokens, expect)
    meta = json.load(open(out + ".json"))
    assert meta["rows"] == 3 and meta["documents"] == 2


def test_boundary_cut(tmp_path, binary):
    # newline (10) as boundary: a row that would split the doc is cut at
    # the last newline and the remainder starts the next row
    docs = ["ab\ncd\nefgh"]
    out = str(tmp_path / "out.tokens")
    run_tokenizer([
        "--input", write_docs(tmp_path, docs), "--output", out,
        "--tokenizer", "byte", "--context-size", "6",
        "--eot-token", "0", "--pad-token", "1",
        "--boundary-token", "10", "--boundary-overlap", "0",
    ], binary=binary)
    tokens = np.fromfile(out, dtype=np.uint16).reshape(-1, 6)
    # row 0 cut after second newline: ab\ncd\n ; row 1: efgh EOT pad
    expect = np.array([
        [97, 98, 10, 99, 100, 10],
        [101, 102, 103, 104, 0, 1],
    ], np.uint16)
    np.testing.assert_array_equal(tokens, expect)


def test_sampling_and_reorder(tmp_path, binary):
    docs = [f"doc{i}" for i in range(20)]
    src = write_docs(tmp_path, docs)
    out_all = str(tmp_path / "all.tokens")
    out_half = str(tmp_path / "half.tokens")
    run_tokenizer(["--input", src, "--output", out_all,
                   "--tokenizer", "byte", "--context-size", "8",
                   "--pad-token", "1"], binary=binary)
    run_tokenizer(["--input", src, "--output", out_half,
                   "--tokenizer", "byte", "--context-size", "8",
                   "--pad-token", "1", "--sampling", "50",
                   "--seed", "7"], binary=binary)
    n_all = json.load(open(out_all + ".json"))["documents"]
    n_half = json.load(open(out_half + ".json"))["documents"]
    assert n_all == 20 and 3 <= n_half <= 17

    out_shuf = str(tmp_path / "shuf.tokens")
    run_tokenizer(["--input", src, "--output", out_shuf,
                   "--tokenizer", "byte", "--context-size", "8",
                   "--pad-token", "1", "--reorder", "shuffle",
                   "--seed", "3"], binary=binary)
    a = np.fromfile(out_all, np.uint16)
    b = np.fromfile(out_shuf, np.uint16)
    assert a.shape == b.shape and not np.array_equal(a, b)
    assert np.array_equal(np.sort(a), np.sort(b))


def test_sanitize(tmp_path, binary):
    docs = ["a \t  b\x07c\n\nd"]
    out = str(tmp_path / "san.tokens")
    run_tokenizer(["--input", write_docs(tmp_path, docs), "--output", out,
                   "--tokenizer", "byte", "--context-size", "16",
                   "--eot-token", "0", "--pad-token", "0",
                   "--sanitize"], binary=binary)
    row = np.fromfile(out, np.uint16)
    text = bytes(t for t in row.tolist() if t not in (0,)).decode()
    assert text == "a bc\n\nd"


def test_cli_errors(tmp_path, binary):
    r = run_tokenizer(["--input", "/does/not/exist", "--output",
                       str(tmp_path / "x.tokens"), "--context-size", "8"],
                      binary=binary, check=False)
    assert r.returncode != 0
    r = run_tokenizer(["--nonsense"], binary=binary, check=False)
    assert r.returncode != 0


def test_bpe_matches_hf_tokenizers(tmp_path, binary):
    tokenizers = pytest.importorskip("tokenizers")

    # build a small BPE over ASCII from a corpus, then compare encodings
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "hello world, hello tpu! it's running 123 tests.",
        "pack my box with five dozen liquor jugs?",
    ]
    tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token=None))
    tok.pre_tokenizer = tokenizers.pre_tokenizers.ByteLevel(
        add_prefix_space=False)
    tok.decoder = tokenizers.decoders.ByteLevel()
    trainer = tokenizers.trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<|endoftext|>"],
        initial_alphabet=tokenizers.pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(corpus, trainer)
    vocab_path = str(tmp_path / "vocab.json")
    merges_path = str(tmp_path / "merges.txt")
    model_files = tok.model.save(str(tmp_path))
    for f in model_files:
        if f.endswith("vocab.json"):
            os.replace(f, vocab_path)
        elif f.endswith("merges.txt"):
            os.replace(f, merges_path)

    text = "the quick brown fox, it's 123 jugs over the lazy dog!"
    expect = tok.encode(text).ids

    doc_dir = tmp_path / "docs"
    doc_dir.mkdir()
    (doc_dir / "a.txt").write_text(text)
    out = str(tmp_path / "bpe.tokens")
    run_tokenizer([
        "--input", str(doc_dir), "--output", out,
        "--tokenizer", "bpe", "--vocab", vocab_path,
        "--merges", merges_path, "--context-size", "64",
        "--eot-token", "0", "--pad-token", "0",
    ], binary=binary)
    got = np.fromfile(out, np.uint16).tolist()
    got = [t for t in got if t != 0]  # strip eot+pad (id 0)
    expect = [t for t in expect if t != 0]
    assert got == expect, f"\nexpect {expect}\ngot    {got}"


def test_tokenized_dataset_and_masks(tmp_path, binary):
    docs = ["abc", "defgh"]
    out = str(tmp_path / "ds.tokens")
    run_tokenizer(["--input", write_docs(tmp_path, docs), "--output", out,
                   "--tokenizer", "byte", "--context-size", "4",
                   "--eot-token", "0", "--pad-token", "1"], binary=binary)
    ds = TokenizedDataset(out)  # reads sidecar
    assert len(ds) == 3 and ds.context_size == 4
    row = ds[2]
    np.testing.assert_array_equal(row["input_ids"], [104, 0, 1, 1])
    np.testing.assert_array_equal(row["attention_mask"], [1, 1, 0, 0])
    # mid-row pad ids stay visible (pad == eot case)
    row0 = ds[0]
    np.testing.assert_array_equal(row0["attention_mask"], [1, 1, 1, 1])
    train, val = ds.split(2 / 3)
    assert len(train) == 2 and len(val) == 1
    np.testing.assert_array_equal(val[0]["input_ids"], row["input_ids"])


def test_sharded_batches(tmp_path, binary, devices8):
    from kubernetes_cloud_tpu.core import MeshSpec, build_mesh
    from kubernetes_cloud_tpu.data import sharded_batches

    docs = [chr(ord("a") + i) * 7 for i in range(8)]
    out = str(tmp_path / "sb.tokens")
    run_tokenizer(["--input", write_docs(tmp_path, docs), "--output", out,
                   "--tokenizer", "byte", "--context-size", "8",
                   "--eot-token", "0", "--pad-token", "1"], binary=binary)
    ds = TokenizedDataset(out)
    mesh = build_mesh(MeshSpec(data=4, fsdp=2), devices=devices8)
    it = sharded_batches(ds, 8, mesh, shuffle=True, seed=0, epochs=1)
    batches = list(it)
    assert len(batches) == 1
    batch = batches[0]
    assert batch["input_ids"].shape == (8, 8)
    from jax.sharding import PartitionSpec as P
    assert batch["input_ids"].sharding.spec[0] == ("data", "fsdp")
