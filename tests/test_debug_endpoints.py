"""/debug/* performance-introspection plane over the real serving
stack: flight-recorder timeline with phase timings on both front-ends,
TTFT decomposed into queue-wait vs prefill-compute (predictions, spans,
and the request ring), the phase-labeled iteration histogram, the paged
/debug/pages view matching the fixed kv-utilization gauge, the
jax.profiler window, and the chaos proof that a hung or raising
``debug.render`` leaves generate and /readyz untouched."""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.obs import flops as obs_flops
from kubernetes_cloud_tpu.obs import tracing
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _clean():
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()
    yield
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()


@pytest.fixture(scope="module")
def service():
    svc = CausalLMService("lm", CFG,
                          params=init_params(CFG, jax.random.key(0)),
                          dtype=jnp.float32)
    svc.load()
    return svc


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _predict(port, prompt, max_new=4, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps({"instances": [prompt],
                         "parameters": {"max_new_tokens": max_new,
                                        "temperature": 0.0}}).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def served(service):
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64))
    model.load()
    srv = ModelServer([model], host="127.0.0.1", port=0)
    srv.start()
    yield srv, model
    srv.stop()
    model.stop()


def test_timeline_phases_and_ttft_decomposition(served, tmp_path):
    srv, model = served
    tracing.install(tracing.RequestTracer(str(tmp_path / "t.jsonl")))
    code, body = _predict(srv.port, "introspect me", max_new=6,
                          headers={"X-Request-Id": "dbg-1"})
    assert code == 200
    pred = body["predictions"][0]
    # per-prediction TTFT decomposition: the components partition TTFT
    assert pred["ttft_queue_s"] >= 0 and pred["ttft_prefill_s"] > 0
    assert pred["ttft_queue_s"] + pred["ttft_prefill_s"] \
        == pytest.approx(pred["ttft_s"], abs=2e-6)
    # spans carry the same split
    spans = {r["span"]: r for r in tracing.active().spans_for("dbg-1")}
    assert spans["admitted"]["queue_s"] >= 0
    assert spans["first_token"]["prefill_s"] > 0

    code, dump = _get(srv.port, "/debug/timeline?last=64")
    assert code == 200
    entry = dump["models"]["lm"]
    assert entry["kind"] == "engine"
    iters = entry["iterations"]
    assert iters  # the run landed on the ring
    prefill_recs = [r for r in iters if r["admitted"]]
    decode_recs = [r for r in iters
                   if not r["admitted"] and r["decode_tokens"]]
    assert prefill_recs and decode_recs
    assert prefill_recs[0]["phases"]["prefill"] > 0
    assert prefill_recs[0]["prefill_tokens"] == len("introspect me")
    for r in decode_recs:
        assert r["phases"]["decode"] > 0
        assert r["phases"]["host_sync"] >= 0
        assert r["phases"]["sample"] > 0
        assert set(r["phases"]) <= set(obs.flight.PHASES)
        assert r["flops"] > 0
    # seq strictly increases across the dump
    seqs = [r["seq"] for r in iters]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # ?last filters
    assert len(_get(srv.port,
                    "/debug/timeline?last=1")[1]["models"]["lm"]
               ["iterations"]) == 1
    # meta carries the analytical constants the analyzer needs
    base, per_ctx = obs_flops.decode_flops_coeffs(CFG)
    assert entry["meta"]["flops_base"] == base
    assert entry["meta"]["flops_per_ctx"] == per_ctx
    # the request ring carries the same decomposition
    reqs = entry["requests"]
    assert reqs[-1]["outcome"] == "complete"
    assert reqs[-1]["queue_s"] + reqs[-1]["prefill_s"] \
        == pytest.approx(reqs[-1]["ttft_s"], abs=2e-6)


def test_debug_slots_shows_occupancy(served):
    srv, model = served
    _predict(srv.port, "warm", max_new=2)
    code, body = _get(srv.port, "/debug/slots")
    assert code == 200
    slots = body["models"]["lm"]["slots"]
    assert len(slots) == 2  # EngineConfig(slots=2)
    assert all(s["state"] == "free" for s in slots)  # drained
    # occupy a slot mid-flight and observe it decoding
    eng = model.engine
    req = eng.submit([1, 2, 3], max_new_tokens=40, temperature=0.0)
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            slots = _get(srv.port, "/debug/slots")[1]["models"]["lm"]
            busy = [s for s in slots["slots"]
                    if s["state"] == "decoding"]
            if busy:
                break
        assert busy and busy[0]["prompt_tokens"] == 3
        assert busy[0]["max_new_tokens"] == 40
    finally:
        req.cancel()
        req.event.wait(timeout=10)


def test_phase_labeled_iteration_histogram_and_gauges(served):
    srv, _ = served
    _predict(srv.port, "phase split", max_new=24)
    time.sleep(0.7)  # cross the 0.5s gauge-refresh gate
    samples = obs.parse_text(obs.render_text())
    prefill_n = obs.sample_value(
        samples, "kct_engine_iteration_seconds_count",
        {"model": "lm", "phase": "prefill"})
    decode_n = obs.sample_value(
        samples, "kct_engine_iteration_seconds_count",
        {"model": "lm", "phase": "decode"})
    assert prefill_n >= 1  # the admission pass
    assert decode_n >= 20  # one per decode-only iteration
    assert obs.sample_value(samples, "kct_engine_phase_seconds_total",
                            {"model": "lm", "phase": "decode"}) > 0
    assert obs.sample_value(samples, "kct_engine_phase_seconds_total",
                            {"model": "lm", "phase": "prefill"}) > 0
    assert obs.sample_value(samples,
                            "kct_engine_goodput_tokens_per_s",
                            {"model": "lm"}) > 0
    # CPU host: no peak in the device table, so MFU honestly reads 0
    assert obs.sample_value(samples, "kct_engine_mfu",
                            {"model": "lm"}) == 0


def test_flight_records_zero_disables_recording(service):
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64, flight_records=0))
    model.load()
    srv = ModelServer([model], host="127.0.0.1", port=0)
    srv.start()
    try:
        assert _predict(srv.port, "no recorder", max_new=3)[0] == 200
        code, dump = _get(srv.port, "/debug/timeline")
        assert code == 200
        assert dump["models"] == {}  # nothing carries a recorder
    finally:
        srv.stop()
        model.stop()


def test_paged_debug_pages_matches_kv_utilization_gauge(service):
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64, paged=True, page_size=16))
    model.load()
    srv = ModelServer([model], host="127.0.0.1", port=0)
    srv.start()
    eng = model.engine
    try:
        # slow every scheduler pass so the request stays verifiably
        # in flight while we compare the debug view with the gauge
        with faults.inject(FaultSpec("iteration", mode="slow",
                                     delay_s=0.05, times=-1)):
            req = eng.submit(list(range(1, 20)), max_new_tokens=40,
                             temperature=0.0)
            deadline = time.monotonic() + 10
            pages = None
            while time.monotonic() < deadline:
                code, body = _get(srv.port, "/debug/pages")
                assert code == 200
                pages = body["models"]["lm"]
                if pages and pages.get("used_pages") \
                        and len(req.tokens) >= 1:
                    break
            # 19 prompt + 40 new = 59 rows → 4 pages of 16
            assert pages["used_pages"] == 4
            assert pages["page_size"] == 16
            assert pages["utilization"] == pytest.approx(
                4 / pages["capacity"])
            assert pages["reserved_rows"] == 64
            assert 0.0 <= pages["fragmentation"] <= 1.0
            # the FIXED gauge reports the same number (page-arena
            # utilization, not live-token-rows).  It refreshes at the
            # top of each scheduler pass, so poll a bounded window —
            # the slowed iterations keep the request in flight far
            # longer than one refresh period.
            want = pages["utilization"]
            deadline = time.monotonic() + 5
            got = None
            while time.monotonic() < deadline:
                samples = obs.parse_text(obs.render_text())
                got = obs.sample_value(samples,
                                       "kct_engine_kv_utilization",
                                       {"model": "lm"})
                if got == pytest.approx(want, abs=1e-6):
                    break
                time.sleep(0.02)
            assert got == pytest.approx(want, abs=1e-6)
            req.cancel()
        req.event.wait(timeout=10)
        # after release the pages park in the prefix cache (LRU),
        # exposed as hashes with refcount 0 — never token content
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            pages = _get(srv.port, "/debug/pages")[1]["models"]["lm"]
            if not pages.get("used_pages"):
                break
        assert pages["used_pages"] == 0
        cache = pages["prefix_cache"]
        assert cache  # the full prompt block was published
        assert all(set(e) == {"page", "hash", "refcount",
                              "lru_position"} for e in cache)
        assert all(e["refcount"] == 0 for e in cache)
    finally:
        srv.stop()
        model.stop()


def test_dense_debug_pages_is_null(served):
    srv, _ = served
    code, body = _get(srv.port, "/debug/pages")
    assert code == 200
    assert body["models"]["lm"] is None  # dense pool: no arena


def test_debug_unknown_endpoint_and_bad_params(served):
    srv, _ = served
    code, body = _get(srv.port, "/debug/nope")
    assert code == 404 and "endpoints" in body
    assert _get(srv.port, "/debug/timeline?last=-3")[0] == 400
    assert _get(srv.port, "/debug/timeline?last=junk")[0] == 400


def test_profile_window_arm_conflict_rearm(served, tmp_path):
    srv, _ = served
    # the process's FIRST start_trace pays ~10s of profiler-server
    # init; warm it here so the HTTP window below is fast (a real pod
    # pays this once, on its first armed window)
    jax.profiler.start_trace(str(tmp_path / "warm"))
    jax.profiler.stop_trace()
    srv.profiler.trace_dir = str(tmp_path / "trace")
    code, body = _get(srv.port, "/debug/profile?seconds=0.4")
    assert code == 200
    assert body["profiling_s"] == 0.4
    assert body["trace_dir"] == str(tmp_path / "trace")
    # one window at a time
    assert _get(srv.port, "/debug/profile?seconds=1")[0] == 409
    assert srv.profiler.wait(timeout=10)
    # the trace landed and the window can re-arm
    assert (tmp_path / "trace").exists()
    code, _ = _get(srv.port, "/debug/profile?seconds=0.2")
    assert code == 200
    assert srv.profiler.wait(timeout=10)
    # bad durations are 400s
    assert _get(srv.port, "/debug/profile?seconds=0")[0] == 400
    assert _get(srv.port, "/debug/profile?seconds=9999")[0] == 400


@pytest.mark.chaos
def test_raising_debug_render_is_contained(served):
    srv, _ = served
    with faults.inject(FaultSpec("debug.render", mode="raise",
                                 times=-1)):
        code, body = _get(srv.port, "/debug/timeline")
        assert code == 500
        assert "debug unavailable" in body["error"]
        # data plane + readiness untouched
        assert _predict(srv.port, "still serving", max_new=2)[0] == 200
        assert _get(srv.port, "/readyz")[0] == 200
    assert _get(srv.port, "/debug/timeline")[0] == 200  # recovers


@pytest.mark.chaos
def test_hanging_debug_render_is_contained(served):
    srv, _ = served
    with faults.inject(FaultSpec("debug.render", mode="hang",
                                 delay_s=30.0)) as inj:
        done = threading.Event()

        def dump():
            _get(srv.port, "/debug/timeline")
            done.set()

        t = threading.Thread(target=dump, daemon=True)
        t.start()
        time.sleep(0.05)  # the dump thread is parked in the hang
        assert not done.is_set()
        # generate + readiness answer while the debug plane is wedged
        assert _predict(srv.port, "wedged debug", max_new=2)[0] == 200
        assert _get(srv.port, "/readyz")[0] == 200
        with urllib.request.urlopen(  # /metrics is text, not JSON
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            assert r.status == 200
        inj.release()
        t.join(timeout=10)
        assert done.is_set()


def test_native_frontend_debug_parity(service):
    from kubernetes_cloud_tpu.serve import native_server

    if not native_server.available():
        pytest.skip("no C++ toolchain")
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64))
    model.load()
    srv = native_server.NativeModelServer([model], host="127.0.0.1",
                                          port=0)
    srv.start()
    try:
        assert _predict(srv.port, "native debug", max_new=3)[0] == 200
        code, dump = _get(srv.port, "/debug/timeline?last=16")
        assert code == 200
        entry = dump["models"]["lm"]
        assert entry["iterations"][-1]["phases"]
        assert entry["requests"][-1]["outcome"] == "complete"
        assert _get(srv.port, "/debug/slots")[0] == 200
        assert _get(srv.port, "/debug/pages")[0] == 200
        assert _get(srv.port, "/debug/nope")[0] == 404
    finally:
        srv.stop()
        model.stop()


def test_engine_restart_gets_fresh_ring(service):
    """A supervisor-style rebuild starts a fresh recorder — the ring
    documents one engine incarnation (like stats)."""
    eng = ContinuousBatchingEngine(
        CFG, service.params, EngineConfig(slots=1, max_len=64),
        pad_token_id=0, name="lm")
    eng.start()
    try:
        eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait(eng)
        # wait() wakes on the final token's event; the scheduler
        # commits that pass's iteration record a few µs later — give
        # it the tail of its pass before reading the ring
        deadline = time.monotonic() + 5
        while len(eng.flight) == 0 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(eng.flight) > 0
    finally:
        eng.stop()
    replacement = ContinuousBatchingEngine(
        CFG, service.params, EngineConfig(slots=1, max_len=64),
        pad_token_id=0, name="lm")
    assert len(replacement.flight) == 0
