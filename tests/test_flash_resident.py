"""Batch-folded resident flash kernel (ops/flash_resident) vs XLA, and the
``attn_island`` remat policies built on it.

Interpreter mode on CPU; the same code compiles via Mosaic on TPU.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.ops.attention import _mha_xla
from kubernetes_cloud_tpu.ops.flash_resident import (
    _plan,
    flash_mha_resident,
    supported,
)

pytestmark = pytest.mark.slow  # interpret-mode kernels are minutes on 1 CPU


@pytest.fixture(autouse=True)
def _exact_matmuls():
    with jax.default_matmul_precision("highest"):
        yield


def _ref(q, k, v, *, slopes=None, causal=True):
    d = q.shape[-1]
    bias = None
    if slopes is not None:
        kpos = jnp.arange(k.shape[2], dtype=jnp.float32)
        bias = slopes[None, :, None, None] * kpos[None, None, None, :]
    out = _mha_xla(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                   v.transpose(0, 2, 1, 3), causal=causal, bias=bias,
                   mask=None, scale=d ** -0.5)
    return out.transpose(0, 2, 1, 3)


def _qkv(b=2, h=4, hkv=4, s=256, d=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    return q, k, v


def test_forward_matches_xla():
    q, k, v = _qkv()
    got = flash_mha_resident(q, k, v, causal=True, interpret=True)
    want = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gqa_forward_and_grads():
    # GQA rides the hpb=1 path (one head per 128-lane block), so D=128
    q, k, v = _qkv(h=4, hkv=2, d=128)
    do = jnp.asarray(
        np.random.default_rng(1).standard_normal(q.shape), jnp.float32)

    def loss(fn, *args):
        return (fn(*args) * do).sum()

    f = lambda q, k, v: flash_mha_resident(q, k, v, causal=True,
                                           interpret=True)
    r = lambda q, k, v: _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(r(q, k, v)), rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda *a: loss(f, *a), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: loss(r, *a), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_alibi_slopes_in_kernel():
    q, k, v = _qkv()
    slopes = jnp.asarray([0.5 ** i for i in range(1, 5)], jnp.float32)
    got = flash_mha_resident(q, k, v, slopes=slopes, causal=True,
                             interpret=True)
    want = _ref(q, k, v, slopes=slopes, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_grads_match_xla():
    q, k, v = _qkv(s=512)
    do = jnp.asarray(
        np.random.default_rng(1).standard_normal(q.shape), jnp.float32)

    f = lambda q, k, v: (flash_mha_resident(
        q, k, v, causal=True, interpret=True) * do).sum()
    r = lambda q, k, v: (_ref(q, k, v, causal=True) * do).sum()
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_plan_fits_budget_and_divides():
    for (b, s) in [(16, 1024), (8, 2048), (32, 512), (1, 1024)]:
        plan = _plan(b, s, s, 2)
        assert plan is not None
        bb, bq = plan
        assert b % bb == 0 and s % bq == 0


def test_supported_gates():
    assert supported(16, 1024, 1024, 64, 16, 16)
    assert supported(8, 1024, 1024, 128, 8, 2)        # GQA at D=128
    assert not supported(16, 1024, 512, 64, 16, 16)   # cross-attention
    assert not supported(16, 1000, 1000, 64, 16, 16)  # unaligned
    assert not supported(16, 1024, 1024, 64, 16, 3)   # h % hkv
    assert not supported(16, 1024, 1024, 64, 16, 8)   # D<128 GQA (packing)
    assert not supported(16, 1024, 1024, 96, 16, 16)  # 96 lanes unpackable
    assert not supported(16, 1024, 1024, 256, 16, 16)  # D>128 (gpt-j) —
    # kernels hard-code one 128-lane block per head; routes to general


def test_attn_island_policy_matches_dense(monkeypatch):
    """Full-model parity: attn_island remat ≡ attn_mlp remat numerics."""
    from kubernetes_cloud_tpu.models.causal_lm import (
        PRESETS, init_params, loss_fn)

    cfg0 = dataclasses.replace(
        PRESETS["test-tiny"], hidden_size=128, num_heads=2, num_layers=2,
        vocab_size=512, max_seq_len=256, remat=True,
        dtype=jnp.float32, param_dtype=jnp.float32)
    ids = jax.random.randint(jax.random.key(0), (2, 256), 0, 512,
                             dtype=jnp.int32)
    batch = {"input_ids": ids}
    params = init_params(cfg0, jax.random.key(1))

    def run(policy, impl):
        cfg = dataclasses.replace(cfg0, remat_policy=policy, attn_impl=impl)
        return jax.value_and_grad(loss_fn, argnums=1, has_aux=True)(
            cfg, params, batch)

    monkeypatch.setenv("KCT_FLASH_INTERPRET", "1")
    (l0, _), g0 = run("attn_mlp", "xla")
    for policy in ("attn_island", "attn_island_mlp"):
        (l1, _), g1 = run(policy, "pallas")
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)
