"""Diffusers SD checkpoint import: golden numeric parity vs torch.

The environment has no ``diffusers`` package, so the reference modules are
reimplemented here in torch with *diffusers' exact module naming* — their
``state_dict()`` keys are therefore byte-identical to a real SD snapshot's,
which is what makes these tests meaningful: the same converter that passes
here consumes a real ``runwayml``-style checkpoint unchanged.  The CLIP
text encoder is golden-tested against transformers' real ``CLIPTextModel``.

Architecture facts encoded in the torch refs (GroupNorm eps 1e-6, geglu
erf-gelu, UNet downsampler symmetric padding vs VAE's (0,1) asymmetric,
``[cos|sin]`` flipped timestep embedding) mirror the public SD-1.x model
definitions.
"""

import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as nn
import torch.nn.functional as F

from kubernetes_cloud_tpu.models.diffusion.clip_text import clip_encode
from kubernetes_cloud_tpu.models.diffusion.unet import unet_apply
from kubernetes_cloud_tpu.models.diffusion.vae import (
    _encode_moments,
    vae_decode,
)
from kubernetes_cloud_tpu.weights.sd_import import (
    clip_config_from_diffusers,
    import_clip_text,
    import_unet,
    import_vae,
    unet_config_from_diffusers,
    vae_config_from_diffusers,
)

pytestmark = pytest.mark.slow

GROUPS = 4


@pytest.fixture(autouse=True)
def _exact_matmuls():
    with jax.default_matmul_precision("highest"):
        yield


def _t(rng, *shape):
    return torch.tensor(rng.standard_normal(shape), dtype=torch.float32)


# ---------------------------------------------------------------- torch refs

class TResnet(nn.Module):
    def __init__(self, cin, cout, temb_dim=None):
        super().__init__()
        self.norm1 = nn.GroupNorm(GROUPS, cin, eps=1e-6)
        self.conv1 = nn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = nn.GroupNorm(GROUPS, cout, eps=1e-6)
        self.conv2 = nn.Conv2d(cout, cout, 3, padding=1)
        if temb_dim is not None:
            self.time_emb_proj = nn.Linear(temb_dim, cout)
        if cin != cout:
            self.conv_shortcut = nn.Conv2d(cin, cout, 1)

    def forward(self, x, temb=None):
        h = self.conv1(F.silu(self.norm1(x)))
        if temb is not None and hasattr(self, "time_emb_proj"):
            h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "conv_shortcut"):
            x = self.conv_shortcut(x)
        return x + h


class TVAEAttn(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.group_norm = nn.GroupNorm(GROUPS, c, eps=1e-6)
        self.to_q = nn.Linear(c, c)
        self.to_k = nn.Linear(c, c)
        self.to_v = nn.Linear(c, c)
        self.to_out = nn.ModuleList([nn.Linear(c, c)])

    def forward(self, x):
        b, c, h, w = x.shape
        y = self.group_norm(x).reshape(b, c, h * w).transpose(1, 2)
        q, k, v = self.to_q(y), self.to_k(y), self.to_v(y)
        a = torch.softmax(q @ k.transpose(1, 2) * c ** -0.5, dim=-1) @ v
        return x + self.to_out[0](a).transpose(1, 2).reshape(b, c, h, w)


class TMid(nn.Module):
    def __init__(self, c, temb_dim=None, attn_cls=TVAEAttn, **kw):
        super().__init__()
        self.resnets = nn.ModuleList([TResnet(c, c, temb_dim),
                                      TResnet(c, c, temb_dim)])
        self.attentions = nn.ModuleList([attn_cls(c, **kw)])

    def forward(self, x, temb=None, ctx=None):
        x = self.resnets[0](x, temb)
        x = (self.attentions[0](x) if ctx is None
             else self.attentions[0](x, ctx))
        return self.resnets[1](x, temb)


class THasConv(nn.Module):
    def __init__(self, conv):
        super().__init__()
        self.conv = conv


class TVAEEncoder(nn.Module):
    def __init__(self, chans, cin, latent, layers):
        super().__init__()
        self.conv_in = nn.Conv2d(cin, chans[0], 3, padding=1)
        self.down_blocks = nn.ModuleList()
        c = chans[0]
        for i, cout in enumerate(chans):
            blk = nn.Module()
            blk.resnets = nn.ModuleList()
            for _ in range(layers):
                blk.resnets.append(TResnet(c, cout))
                c = cout
            if i < len(chans) - 1:
                # VAE downsampler: padding=0 conv + manual (0,1,0,1) pad
                blk.downsamplers = nn.ModuleList(
                    [THasConv(nn.Conv2d(c, c, 3, stride=2))])
            self.down_blocks.append(blk)
        self.mid_block = TMid(chans[-1])
        self.conv_norm_out = nn.GroupNorm(GROUPS, chans[-1], eps=1e-6)
        self.conv_out = nn.Conv2d(chans[-1], 2 * latent, 3, padding=1)

    def forward(self, x):
        h = self.conv_in(x)
        for blk in self.down_blocks:
            for r in blk.resnets:
                h = r(h)
            if hasattr(blk, "downsamplers"):
                h = blk.downsamplers[0].conv(F.pad(h, (0, 1, 0, 1)))
        h = self.mid_block(h)
        return self.conv_out(F.silu(self.conv_norm_out(h)))


class TVAEDecoder(nn.Module):
    def __init__(self, chans, cout_img, latent, layers):
        super().__init__()
        rev = list(reversed(chans))
        self.conv_in = nn.Conv2d(latent, rev[0], 3, padding=1)
        self.mid_block = TMid(rev[0])
        self.up_blocks = nn.ModuleList()
        c = rev[0]
        for i, cout in enumerate(rev):
            blk = nn.Module()
            blk.resnets = nn.ModuleList()
            for _ in range(layers + 1):
                blk.resnets.append(TResnet(c, cout))
                c = cout
            if i < len(chans) - 1:
                blk.upsamplers = nn.ModuleList(
                    [THasConv(nn.Conv2d(c, c, 3, padding=1))])
            self.up_blocks.append(blk)
        self.conv_norm_out = nn.GroupNorm(GROUPS, chans[0], eps=1e-6)
        self.conv_out = nn.Conv2d(chans[0], cout_img, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(z)
        h = self.mid_block(h)
        for blk in self.up_blocks:
            for r in blk.resnets:
                h = r(h)
            if hasattr(blk, "upsamplers"):
                h = F.interpolate(h, scale_factor=2, mode="nearest")
                h = blk.upsamplers[0].conv(h)
        return self.conv_out(F.silu(self.conv_norm_out(h)))


class TVAE(nn.Module):
    def __init__(self, chans=(8, 16), cin=3, latent=4, layers=1):
        super().__init__()
        self.encoder = TVAEEncoder(chans, cin, latent, layers)
        self.decoder = TVAEDecoder(chans, cin, latent, layers)
        self.quant_conv = nn.Conv2d(2 * latent, 2 * latent, 1)
        self.post_quant_conv = nn.Conv2d(latent, latent, 1)


class TCrossAttn(nn.Module):
    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(dim, dim, bias=False)
        self.to_k = nn.Linear(ctx_dim, dim, bias=False)
        self.to_v = nn.Linear(ctx_dim, dim, bias=False)
        self.to_out = nn.ModuleList([nn.Linear(dim, dim)])

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, s, c = x.shape
        h, dh = self.heads, c // self.heads
        q = self.to_q(x).reshape(b, s, h, dh).transpose(1, 2)
        k = self.to_k(ctx).reshape(b, -1, h, dh).transpose(1, 2)
        v = self.to_v(ctx).reshape(b, -1, h, dh).transpose(1, 2)
        o = F.scaled_dot_product_attention(q, k, v)
        return self.to_out[0](o.transpose(1, 2).reshape(b, s, c))


class TGEGLU(nn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.proj = nn.Linear(din, 2 * dout)

    def forward(self, x):
        h, gate = self.proj(x).chunk(2, dim=-1)
        return h * F.gelu(gate)


class TFeedForward(nn.Module):
    def __init__(self, dim):
        super().__init__()
        self.net = nn.ModuleList([TGEGLU(dim, 4 * dim), nn.Identity(),
                                  nn.Linear(4 * dim, dim)])

    def forward(self, x):
        return self.net[2](self.net[1](self.net[0](x)))


class TBasicBlock(nn.Module):
    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = TCrossAttn(dim, dim, heads)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = TCrossAttn(dim, ctx_dim, heads)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = TFeedForward(dim)

    def forward(self, x, ctx):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), ctx)
        return x + self.ff(self.norm3(x))


class TTransformer2D(nn.Module):
    def __init__(self, c, ctx_dim, heads):
        super().__init__()
        self.norm = nn.GroupNorm(GROUPS, c, eps=1e-6)
        self.proj_in = nn.Conv2d(c, c, 1)  # SD-1.x: conv projection
        self.transformer_blocks = nn.ModuleList(
            [TBasicBlock(c, ctx_dim, heads)])
        self.proj_out = nn.Conv2d(c, c, 1)

    def forward(self, x, ctx):
        b, c, h, w = x.shape
        res = x
        y = self.proj_in(self.norm(x))
        y = y.reshape(b, c, h * w).transpose(1, 2)
        y = self.transformer_blocks[0](y, ctx)
        y = y.transpose(1, 2).reshape(b, c, h, w)
        return self.proj_out(y) + res


class TTimeEmbedding(nn.Module):
    def __init__(self, cin, dim):
        super().__init__()
        self.linear_1 = nn.Linear(cin, dim)
        self.linear_2 = nn.Linear(dim, dim)

    def forward(self, x):
        return self.linear_2(F.silu(self.linear_1(x)))


def _t_timestep_embedding(t, dim):
    """Diffusers ``Timesteps``: flip_sin_to_cos=True, freq_shift=0."""
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0)
                      * torch.arange(half, dtype=torch.float32) / half)
    args = t.float()[:, None] * freqs[None]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


class TUNet(nn.Module):
    def __init__(self, chans=(8, 16), cin=4, cout=4, layers=1,
                 ctx_dim=12, heads=2):
        super().__init__()
        self.chans, self.heads = chans, heads
        n = len(chans)
        temb = 4 * chans[0]
        self.time_embedding = TTimeEmbedding(chans[0], temb)
        self.conv_in = nn.Conv2d(cin, chans[0], 3, padding=1)

        self.down_blocks = nn.ModuleList()
        c = chans[0]
        for i, co in enumerate(chans):
            blk = nn.Module()
            blk.resnets = nn.ModuleList()
            if i < n - 1:  # CrossAttn block (SD: all but innermost)
                blk.attentions = nn.ModuleList()
            for _ in range(layers):
                blk.resnets.append(TResnet(c, co, temb))
                c = co
                if hasattr(blk, "attentions"):
                    blk.attentions.append(TTransformer2D(co, ctx_dim, heads))
            if i < n - 1:
                # UNet downsampler: symmetric padding=1, unlike the VAE's
                blk.downsamplers = nn.ModuleList(
                    [THasConv(nn.Conv2d(c, c, 3, stride=2, padding=1))])
            self.down_blocks.append(blk)

        self.mid_block = TMid(chans[-1], temb, attn_cls=TTransformer2D,
                              ctx_dim=ctx_dim, heads=heads)

        skip = [chans[0]]
        c2 = chans[0]
        for i, co in enumerate(chans):
            for _ in range(layers):
                skip.append(co)
                c2 = co
            if i < n - 1:
                skip.append(co)

        self.up_blocks = nn.ModuleList()
        c = chans[-1]
        for i, co in enumerate(reversed(chans)):
            blk = nn.Module()
            blk.resnets = nn.ModuleList()
            if (n - 1 - i) < n - 1:
                blk.attentions = nn.ModuleList()
            for _ in range(layers + 1):
                blk.resnets.append(TResnet(c + skip.pop(), co, temb))
                c = co
                if hasattr(blk, "attentions"):
                    blk.attentions.append(TTransformer2D(co, ctx_dim, heads))
            if i < n - 1:
                blk.upsamplers = nn.ModuleList(
                    [THasConv(nn.Conv2d(c, c, 3, padding=1))])
            self.up_blocks.append(blk)

        self.conv_norm_out = nn.GroupNorm(GROUPS, chans[0], eps=1e-6)
        self.conv_out = nn.Conv2d(chans[0], cout, 3, padding=1)

    def forward(self, x, t, ctx):
        temb = self.time_embedding(_t_timestep_embedding(t, self.chans[0]))
        h = self.conv_in(x)
        skips = [h]
        for blk in self.down_blocks:
            for j, r in enumerate(blk.resnets):
                h = r(h, temb)
                if hasattr(blk, "attentions"):
                    h = blk.attentions[j](h, ctx)
                skips.append(h)
            if hasattr(blk, "downsamplers"):
                h = blk.downsamplers[0].conv(h)
                skips.append(h)
        h = self.mid_block(h, temb, ctx)
        for blk in self.up_blocks:
            for j, r in enumerate(blk.resnets):
                h = r(torch.cat([h, skips.pop()], dim=1), temb)
                if hasattr(blk, "attentions"):
                    h = blk.attentions[j](h, ctx)
            if hasattr(blk, "upsamplers"):
                h = F.interpolate(h, scale_factor=2, mode="nearest")
                h = blk.upsamplers[0].conv(h)
        return self.conv_out(F.silu(self.conv_norm_out(h)))


# ------------------------------------------------------------------- configs

VAE_CONFIG = {"in_channels": 3, "latent_channels": 4,
              "block_out_channels": [8, 16], "layers_per_block": 1,
              "norm_num_groups": GROUPS, "scaling_factor": 0.18215}

UNET_CONFIG = {"in_channels": 4, "out_channels": 4,
               "block_out_channels": [8, 16], "layers_per_block": 1,
               "cross_attention_dim": 12, "attention_head_dim": 2,
               "norm_num_groups": GROUPS,
               "down_block_types": ["CrossAttnDownBlock2D", "DownBlock2D"]}


def _nhwc(t: torch.Tensor) -> np.ndarray:
    return t.detach().numpy().transpose(0, 2, 3, 1)


# --------------------------------------------------------------------- tests

def test_vae_import_matches_torch():
    torch.manual_seed(0)
    tvae = TVAE().eval()
    cfg = vae_config_from_diffusers(VAE_CONFIG)
    params = import_vae(cfg, tvae.state_dict())

    rng = np.random.default_rng(0)
    x = _t(rng, 2, 3, 16, 16)
    with torch.no_grad():
        want_moments = tvae.quant_conv(tvae.encoder(x))
    got_moments = _encode_moments(cfg, params, jnp.asarray(_nhwc(x)))
    got_moments = jax.numpy.asarray(got_moments)
    from kubernetes_cloud_tpu.models.diffusion.nn2d import conv2d

    got_moments = conv2d(params["quant_conv"], got_moments)
    np.testing.assert_allclose(np.asarray(got_moments),
                               _nhwc(want_moments), rtol=1e-4, atol=1e-4)

    z = _t(rng, 2, 4, 4, 4)
    with torch.no_grad():
        want_img = tvae.decoder(tvae.post_quant_conv(z))
    # vae_decode takes the *scaled* latent and unscales internally
    got_img = vae_decode(cfg, params,
                         jnp.asarray(_nhwc(z)) * cfg.scaling_factor)
    np.testing.assert_allclose(np.asarray(got_img), _nhwc(want_img),
                               rtol=1e-4, atol=1e-4)


def test_unet_import_matches_torch():
    torch.manual_seed(1)
    tunet = TUNet().eval()
    cfg = unet_config_from_diffusers(UNET_CONFIG)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = import_unet(cfg, tunet.state_dict())

    rng = np.random.default_rng(1)
    x = _t(rng, 2, 4, 8, 8)
    t = torch.tensor([7, 423])
    ctx = _t(rng, 2, 5, 12)
    with torch.no_grad():
        want = tunet(x, t, ctx)
    got = unet_apply(cfg, params, jnp.asarray(_nhwc(x)),
                     jnp.asarray(t.numpy()), jnp.asarray(ctx.numpy()))
    np.testing.assert_allclose(np.asarray(got), _nhwc(want),
                               rtol=2e-4, atol=2e-4)


def test_clip_import_matches_transformers():
    from transformers import CLIPTextConfig as HFConfig
    from transformers import CLIPTextModel

    hf_cfg = HFConfig(vocab_size=99, hidden_size=32, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=16, hidden_act="quick_gelu")
    torch.manual_seed(2)
    model = CLIPTextModel(hf_cfg).eval()

    cfg = clip_config_from_diffusers(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = import_clip_text(cfg, model.state_dict())

    ids = np.random.default_rng(3).integers(0, 99, (2, 16))
    with torch.no_grad():
        want = model(torch.tensor(ids)).last_hidden_state
    got = clip_encode(cfg, params, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_strict_rejects_unknown_keys():
    torch.manual_seed(0)
    tvae = TVAE().eval()
    cfg = vae_config_from_diffusers(VAE_CONFIG)
    sd = dict(tvae.state_dict())
    sd["mystery.weight"] = torch.zeros(3)
    with pytest.raises(ValueError, match="mystery"):
        import_vae(cfg, sd)
    # non-strict drops it
    import_vae(cfg, sd, strict=False)


def _build_fake_snapshot(src):
    """Fake diffusers snapshot dir (diffusers' exact file layout);
    returns the tokenizer vocab so callers can assert id framing."""
    from safetensors.torch import save_file

    torch.manual_seed(4)
    # cross-attention width must equal the text encoder's hidden size
    unet_cfg_json = UNET_CONFIG | {"cross_attention_dim": 32}
    for sub, module, cfg_json in (
        ("unet", TUNet(ctx_dim=32), unet_cfg_json),
        ("vae", TVAE(), VAE_CONFIG),
    ):
        d = src / sub
        d.mkdir(parents=True)
        save_file(module.state_dict(),
                  str(d / "diffusion_pytorch_model.safetensors"))
        (d / "config.json").write_text(json.dumps(cfg_json))

    from transformers import CLIPTextConfig as HFConfig
    from transformers import CLIPTextModel

    enc_dir = src / "text_encoder"
    enc_dir.mkdir()

    sched_dir = src / "scheduler"
    sched_dir.mkdir()
    (sched_dir / "scheduler_config.json").write_text(json.dumps({
        "num_train_timesteps": 1000, "beta_start": 0.00085,
        "beta_end": 0.012, "beta_schedule": "scaled_linear",
        "prediction_type": "epsilon"}))

    # CLIP tokenizer assets: byte alphabet + </w> variants + specials,
    # sized exactly to the text encoder's vocab (so ids stay in range)
    from kubernetes_cloud_tpu.serve.clip_bpe import bytes_to_unicode

    alphabet = sorted(set(bytes_to_unicode().values()))
    tok_vocab = {}
    for ch in alphabet:
        tok_vocab[ch] = len(tok_vocab)
    for ch in alphabet:
        tok_vocab[ch + "</w>"] = len(tok_vocab)
    tok_vocab["<|startoftext|>"] = len(tok_vocab)
    tok_vocab["<|endoftext|>"] = len(tok_vocab)
    tok_dir = src / "tokenizer"
    tok_dir.mkdir()
    (tok_dir / "vocab.json").write_text(json.dumps(tok_vocab))
    (tok_dir / "merges.txt").write_text("#version: 0.2\n")
    hf_cfg = HFConfig(vocab_size=len(tok_vocab), hidden_size=32,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=16,
                      hidden_act="quick_gelu")
    save_file(CLIPTextModel(hf_cfg).state_dict(),
              str(enc_dir / "model.safetensors"))
    (enc_dir / "config.json").write_text(json.dumps(hf_cfg.to_dict()))
    return tok_vocab


def test_convert_checkpoint_end_to_end(tmp_path):
    """Fake diffusers snapshot dir → convert → serve via sd_service."""
    from kubernetes_cloud_tpu.serve.sd_service import StableDiffusionService
    from kubernetes_cloud_tpu.weights.sd_import import convert_checkpoint

    src = tmp_path / "snapshot"
    tok_vocab = _build_fake_snapshot(src)
    dest = tmp_path / "serving"
    convert_checkpoint(str(src), str(dest))
    assert os.path.exists(dest / "unet.tensors")
    assert os.path.exists(dest / "tokenizer" / "vocab.json")
    assert os.path.exists(dest / ".ready.txt") or any(
        f.startswith(".ready") or f == "ready.txt" for f in os.listdir(dest))

    svc = StableDiffusionService("sd", str(dest))
    svc.load()
    # real-checkpoint path: prompts go through the imported CLIP BPE
    from kubernetes_cloud_tpu.serve.clip_bpe import CLIPBPECodec  # noqa: F401

    assert svc._tokenize(["a cat"])[0][0] == tok_vocab["<|startoftext|>"]
    img = svc.generate("a tpu in the snow", height=16, width=16, steps=2,
                       guidance_scale=5.0, seed=1)
    assert img.shape == (16, 16, 3) and img.dtype == np.uint8


def test_convert_checkpoint_remote_dest(tmp_path):
    """A remote (object-store) dest routes module writes, tokenizer
    assets, AND the ready sentinel through fsspec instead of failing
    partway with local-FS mkdir/copy errors — the advisor's
    sd_import.py:424 finding."""
    import fsspec

    from kubernetes_cloud_tpu.weights.sd_import import convert_checkpoint

    src = tmp_path / "snapshot"
    _build_fake_snapshot(src)
    dest = "memory://sd-remote-dest/serving"
    convert_checkpoint(str(src), dest)
    fs = fsspec.filesystem("memory")
    for name in ("unet.tensors", "vae.tensors", "encoder.tensors",
                 "tokenizer/vocab.json", "tokenizer/merges.txt"):
        assert fs.exists(f"/sd-remote-dest/serving/{name}"), name
    ready = [p for p in fs.ls("/sd-remote-dest/serving", detail=False)
             if "ready" in str(p)]
    assert ready, "ready sentinel missing on remote dest"
