import argparse

import pytest

from kubernetes_cloud_tpu.utils import DashParser, FuzzyBoolAction, validators


def make_parser():
    p = DashParser(prog="t", exit_on_error=False)
    p.add_argument("--run-name", type=str, default="run")
    p.add_argument("--train-ratio", type=validators.at_most_1(float), default=0.9)
    p.add_argument("--seed", type=validators.at_most_32_bit(int), default=42)
    p.add_bool_argument("--no-resume")
    return p


def test_dash_and_underscore_both_parse():
    p = make_parser()
    assert p.parse_args(["--run-name", "a"]).run_name == "a"
    assert p.parse_args(["--run_name", "b"]).run_name == "b"


def test_fuzzy_bools():
    p = make_parser()
    assert p.parse_args(["--no-resume"]).no_resume is True
    assert p.parse_args(["--no_resume", "false"]).no_resume is False
    assert p.parse_args(["--no-resume=yes"]).no_resume is True
    assert p.parse_args([]).no_resume is False
    with pytest.raises(
        (argparse.ArgumentError, argparse.ArgumentTypeError, SystemExit)
    ):
        p.parse_args(["--no-resume", "maybe"])


def test_validators():
    p = make_parser()
    with pytest.raises((argparse.ArgumentError, SystemExit)):
        p.parse_args(["--train-ratio", "1.5"])
    with pytest.raises((argparse.ArgumentError, SystemExit)):
        p.parse_args(["--seed", str(2 ** 33)])
    assert p.parse_args(["--train-ratio", "0.5"]).train_ratio == 0.5
    assert validators.positive(int)("3") == 3
    with pytest.raises(argparse.ArgumentTypeError):
        validators.positive(int)("0")
    with pytest.raises(argparse.ArgumentTypeError):
        validators.non_negative(float)("-0.1")
    with pytest.raises(argparse.ArgumentTypeError):
        validators.extant_file("/definitely/not/a/file")


def test_memory_usage_smoke():
    from kubernetes_cloud_tpu.core import MemoryUsage
    s = str(MemoryUsage.now())
    assert "Host:" in s
