"""Flight recorder + FLOPs accounting + perf_report analyzer — the
jax-free core of the performance-introspection plane: ring-buffer
wraparound and the bounded-memory proof, rate computation, analytical
FLOPs locked against hand-computed values for the test-config
transformer, the perf_report golden-output lock on a canned timeline,
and the batcher's coarse timeline through a live /debug endpoint."""

import json
import pathlib
import subprocess
import sys
import time
import urllib.request

import pytest

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.obs import flops, report
from kubernetes_cloud_tpu.obs.flight import PHASES, FlightRecorder

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# ring buffer: wraparound + bounded memory
# ---------------------------------------------------------------------------


def _commit_n(fr: FlightRecorder, n: int) -> None:
    for i in range(n):
        rec = fr.begin()
        rec.active = 1
        rec.decode_tokens = i  # distinguishable payload
        fr.commit(rec)


def test_ring_wraparound_keeps_newest():
    fr = FlightRecorder(4, request_capacity=4)
    _commit_n(fr, 10)
    assert len(fr) == 4
    recs = fr.tail()
    assert [r["seq"] for r in recs] == [7, 8, 9, 10]  # oldest first
    assert [r["seq"] for r in fr.tail(2)] == [9, 10]
    assert fr.tail(0) == []
    # request ring wraps independently
    for i in range(9):
        fr.record_request({"request_id": f"r{i}"})
    assert [r["request_id"] for r in fr.request_tail()] \
        == ["r5", "r6", "r7", "r8"]


def test_ring_memory_is_bounded_by_construction():
    """The proof is structural: the backing lists are preallocated at
    capacity and only ever written modulo it — a month of commits holds
    exactly `capacity` records."""
    fr = FlightRecorder(8, request_capacity=2)
    assert len(fr._ring) == 8 and len(fr._reqs) == 2
    _commit_n(fr, 1000)
    for _ in range(1000):
        fr.record_request({"request_id": "x"})
    assert len(fr._ring) == 8 and len(fr._reqs) == 2  # never grew
    assert len(fr) == 8
    assert fr.tail()[-1]["seq"] == 1000


def test_disabled_recorder_is_inert():
    fr = FlightRecorder(0, request_capacity=0)
    assert not fr.enabled
    _commit_n(fr, 5)
    fr.record_request({"request_id": "x"})
    assert len(fr) == 0 and fr.tail() == [] and fr.request_tail() == []
    assert fr.rates() == {"tokens_per_s": 0.0, "flops_per_s": 0.0,
                          "busy_s": 0.0, "span_s": 0.0}
    with pytest.raises(ValueError):
        FlightRecorder(-1)


def test_rates_over_trailing_window():
    fr = FlightRecorder(16)
    now = time.time()
    for i in range(4):
        rec = fr.begin()
        rec.ts = now - 0.4 + i * 0.1  # 4 records spanning 0.3s + dur
        rec.dur_s = 0.1
        rec.decode_tokens = 5
        rec.prefill_tokens = 5
        rec.flops = 100.0
        fr.commit(rec)
    r = fr.rates(window_s=10.0)
    # span = last end - first start = 0.3 + 0.1 = 0.4
    assert r["tokens_per_s"] == pytest.approx(40 / 0.4)
    assert r["flops_per_s"] == pytest.approx(400 / 0.4)
    assert r["busy_s"] == pytest.approx(0.4)
    # a tight window excludes the old records
    assert fr.rates(window_s=0.25)["tokens_per_s"] < 40 / 0.4 + 1e-6


# ---------------------------------------------------------------------------
# analytical FLOPs: locked against hand-computed values for the
# test-config transformer (duck-typed config — no jax import needed)
# ---------------------------------------------------------------------------


class _TinyCfg:
    """The test-tiny architecture as plain attributes (what
    models.causal_lm.PRESETS['test-tiny'] declares, vocab 512)."""

    vocab_size = 512
    hidden_size = 64
    num_layers = 2
    num_heads = 4
    num_kv_heads = None
    intermediate_size = None
    max_seq_len = 128
    pos_emb = "rope"
    use_bias = True
    tie_embeddings = False
    embed_layernorm = False
    moe_experts = 0


def test_decode_flops_coeffs_hand_computed():
    # h=64, L=2, V=512, inter=4h=256, kv_dim=64 (MHA).  Per layer:
    #   qkv 2·64·(64+128)=24576, out 2·64·64=8192, mlp 4·64·256=65536
    # base = 2·(24576+8192+65536) + logits 2·64·512 = 196608+65536
    base, per_ctx = flops.decode_flops_coeffs(_TinyCfg())
    assert base == 262144.0
    # per-context-token attention: 4·h per layer = 2·4·64
    assert per_ctx == 512.0


def test_param_count_hand_computed():
    # embed 512·64=32768; per layer: qkv 64·192+192=12480,
    # out 64·64+64=4160, mlp 2·64·256+(256+64)=33088, norms 4·64=256
    # → 49984; ×2 layers; final norm 128; untied head 32768
    assert flops.param_count(_TinyCfg()) \
        == 32768 + 2 * 49984 + 128 + 32768


def test_span_flops_closed_form_matches_sum():
    base, per_ctx = 10.0, 1.0
    # 3 tokens on top of 2 cached: contexts 3, 4, 5
    assert flops.span_flops(base, per_ctx, 2, 3) \
        == (10 + 3) + (10 + 4) + (10 + 5)
    assert flops.span_flops(base, per_ctx, 0, 0) == 0.0
    # a full prefill == the decode-coeff sum over every position
    total = sum(base + per_ctx * k for k in range(1, 8))
    assert flops.span_flops(base, per_ctx, 0, 7) == total


def test_gqa_and_moe_flops():
    class GQA(_TinyCfg):
        num_kv_heads = 2  # kv_dim 32

    base, per_ctx = flops.decode_flops_coeffs(GQA())
    # qkv shrinks to 2·64·(64+64)=16384/layer; attention compute
    # (per_ctx) is unchanged — GQA saves KV memory, not attention math
    assert base == 2 * (16384 + 8192 + 65536) + 65536
    assert per_ctx == 512.0

    class MoE(_TinyCfg):
        moe_experts = 4
        moe_top_k = 2

    base_moe, _ = flops.decode_flops_coeffs(MoE())
    # MLP runs top_k experts + the router: 2·4·64·256 + 2·64·4
    assert base_moe == 2 * (24576 + 8192 + 2 * 65536 + 2 * 64 * 4) + 65536


def test_mfu_and_peak_env(monkeypatch):
    assert flops.mfu(50.0, 100.0) == 0.5
    assert flops.mfu(50.0, None) == 0.0
    assert flops.mfu(50.0, 0.0) == 0.0
    monkeypatch.setenv(flops.PEAK_ENV, "123.5")
    assert flops.peak_flops_per_s() == 123.5
    monkeypatch.setenv(flops.PEAK_ENV, "junk")
    assert flops.peak_flops_per_s() is None


# ---------------------------------------------------------------------------
# analyzer + perf_report golden output on a canned timeline
# ---------------------------------------------------------------------------


def _canned_entry() -> dict:
    return {
        "meta": {"slots": 4, "paged": False},
        "iterations": [
            {"seq": 1, "ts": 100.0, "dur_s": 0.010, "active": 4,
             "admitted": 2, "evicted": 0, "decode_tokens": 4,
             "prefill_tokens": 50, "cached_tokens": 0, "flops": 5e6,
             "phases": {"admit": 0.001, "prefill": 0.006,
                        "decode": 0.002, "host_sync": 0.0005,
                        "sample": 0.0003, "stream": 0.0002}},
            {"seq": 2, "ts": 100.010, "dur_s": 0.002, "active": 4,
             "admitted": 0, "evicted": 0, "decode_tokens": 4,
             "prefill_tokens": 0, "cached_tokens": 0, "flops": 1e6,
             "phases": {"decode": 0.0015, "host_sync": 0.0002,
                        "sample": 0.0002, "stream": 0.0001}},
            {"seq": 3, "ts": 100.012, "dur_s": 0.002, "active": 4,
             "admitted": 0, "evicted": 2, "decode_tokens": 4,
             "prefill_tokens": 0, "cached_tokens": 0, "flops": 1e6,
             "phases": {"decode": 0.0015, "host_sync": 0.0002,
                        "sample": 0.0002, "stream": 0.0001}},
        ],
        "requests": [
            {"request_id": "r1", "ttft_s": 0.05, "queue_s": 0.01,
             "prefill_s": 0.04, "tokens": 8, "outcome": "complete"},
            {"request_id": "r2", "ttft_s": 0.07, "queue_s": 0.03,
             "prefill_s": 0.04, "tokens": 8, "outcome": "complete"},
        ],
    }


def test_analyze_canned_timeline_exact():
    a = report.analyze(_canned_entry(), peak_flops=1e10)
    it = a["iterations"]
    assert (it["count"], it["prefill_bearing"], it["decode_only"]) \
        == (3, 1, 2)
    assert it["busy_s"] == pytest.approx(0.014)
    assert it["span_s"] == pytest.approx(0.014)  # 100.0 → 100.014
    # phase seconds sum across records
    assert a["phase_seconds"]["decode"] == pytest.approx(0.005)
    assert a["phase_seconds"]["prefill"] == pytest.approx(0.006)
    assert a["phase_share"]["prefill"] == pytest.approx(0.006 / 0.014)
    # stall: it1 (0.010s) > 3× median decode-only (0.002) with
    # 4-2=2 already-active slots delayed by 0.008s
    st = a["stalls"]
    assert st["median_decode_s"] == pytest.approx(0.002)
    assert st["threshold_s"] == pytest.approx(0.006)
    assert st["count"] == 1
    assert st["delayed_slot_steps"] == 2
    assert st["stall_s_total"] == pytest.approx(0.008)
    # TTFT decomposition
    tt = a["ttft"]
    assert tt["n"] == 2
    assert tt["ttft_mean_s"] == pytest.approx(0.06)
    assert tt["queue_mean_s"] == pytest.approx(0.02)
    assert tt["prefill_mean_s"] == pytest.approx(0.04)
    assert tt["queue_share"] == pytest.approx(1 / 3)
    # MFU: 7e6 FLOPs over 0.014s = 5e8/s against 1e10 peak
    mf = a["mfu"]
    assert mf["flops_per_s"] == pytest.approx(5e8)
    assert mf["mfu"] == pytest.approx(0.05)
    assert mf["goodput_tokens_per_s"] == pytest.approx(62 / 0.014)
    assert (mf["decode_tokens"], mf["prefill_tokens"]) == (12, 50)


def test_render_golden_lines():
    text = report.render(report.analyze(_canned_entry(),
                                        peak_flops=1e10), "lm")
    assert "== perf report: lm ==" in text
    assert "iterations: 3 (1 prefill-bearing, 2 decode-only)" in text
    for phase in ("admit", "prefill", "decode", "host_sync", "sample",
                  "stream", "other"):
        assert f"\n  {phase}" in text, phase
    assert "prefill stalls: 1 iterations over 6.00ms" in text
    assert "2 decode-slot steps delayed" in text
    assert "queue-wait      mean 20.00ms" in text
    assert "prefill-compute mean 40.00ms" in text
    assert "queue share of TTFT: 33% - compute-bound" in text
    assert "MFU: 5.00%" in text
    # no-peak mode degrades honestly
    text2 = report.render(report.analyze(_canned_entry()), "lm")
    assert "MFU: n/a (peak unknown" in text2


def test_summarize_embedding_shape():
    s = report.summarize(_canned_entry(), peak_flops=1e10)
    assert s["iterations"] == 3
    assert s["prefill_stalls"] == 1
    assert s["mfu"] == pytest.approx(0.05)
    assert s["ttft_queue_mean_s"] == pytest.approx(0.02)
    assert s["ttft_prefill_mean_s"] == pytest.approx(0.04)
    assert set(s["phase_share"]) <= set(PHASES) | {"other"}


def test_perf_report_cli_on_canned_file(tmp_path):
    dump = {"models": {"lm": _canned_entry()}}
    path = tmp_path / "timeline.json"
    path.write_text(json.dumps(dump))
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_report.py"),
         "--file", str(path), "--json", "--peak-flops", "1e10"],
        capture_output=True, text=True, cwd=str(REPO), check=True)
    parsed = json.loads(out.stdout)
    assert parsed["lm"]["mfu"]["mfu"] == pytest.approx(0.05)
    # human mode prints the report
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_report.py"),
         "--file", str(path)],
        capture_output=True, text=True, cwd=str(REPO), check=True)
    assert "perf report: lm" in out.stdout
    assert "prefill stalls: 1" in out.stdout
    # unknown model exits 1 with the available set on stderr
    bad = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_report.py"),
         "--file", str(path), "--model", "nope"],
        capture_output=True, text=True, cwd=str(REPO))
    assert bad.returncode == 1 and "nope" in bad.stderr


def test_perf_report_loads_jsonl_and_bare_entry(tmp_path):
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import perf_report
    finally:
        sys.path.pop(0)
    entry = _canned_entry()
    bare = tmp_path / "entry.json"
    bare.write_text(json.dumps(entry))
    assert "timeline" in perf_report.load_file(str(bare))["models"]
    jsonl = tmp_path / "records.jsonl"
    jsonl.write_text("\n".join(json.dumps(r)
                               for r in entry["iterations"]))
    loaded = perf_report.load_file(str(jsonl))
    assert len(loaded["models"]["timeline"]["iterations"]) == 3
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text('{"neither": 1}')
        perf_report.load_file(str(bad))


# ---------------------------------------------------------------------------
# batcher's coarse timeline through a live /debug endpoint (jax-free)
# ---------------------------------------------------------------------------


def test_batcher_timeline_served_by_debug_endpoint():
    from kubernetes_cloud_tpu.serve.batcher import (
        BatcherConfig,
        BatchingModel,
    )
    from kubernetes_cloud_tpu.serve.server import ModelServer

    m = BatchingModel("bm", lambda insts, params: list(insts),
                      BatcherConfig(max_batch_size=4))
    m.load()
    srv = ModelServer([m], host="127.0.0.1", port=0)
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/models/bm:predict",
            data=json.dumps({"instances": ["a", "b"]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/timeline?last=10",
                timeout=10) as r:
            dump = json.loads(r.read())
        entry = dump["models"]["bm"]
        assert entry["kind"] == "batcher"
        rec = entry["iterations"][-1]
        assert rec["active"] == 1  # one batch
        assert rec["decode_tokens"] == 2  # two instances
        assert set(rec["phases"]) == {"admit", "decode"}
        # /debug/slots has nothing for a batcher, and says so cleanly
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/slots",
                timeout=10) as r:
            assert json.loads(r.read()) == {"models": {}}
    finally:
        srv.stop()
        m.stop()
        obs.REGISTRY.reset()
