"""Debug checks (checkify/finite guards), profiler hooks, k8s/VirtualServer
clients (reference ``virtual-server/examples/python``)."""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu.core.debug import (
    assert_tree_finite,
    checked,
    profile_trace,
)
from kubernetes_cloud_tpu.deploy.k8s_client import ApiError, K8sClient
from kubernetes_cloud_tpu.deploy.vsclient import VirtualServerClient


class TestChecked:
    def test_nan_raises(self):
        def f(x):
            return jnp.log(x)

        cf = checked(f)  # checked() jits internally
        cf(jnp.ones(3))  # fine
        with pytest.raises(Exception, match="nan"):
            cf(-jnp.ones(3))

    def test_oob_raises(self):
        def f(x, i):
            return x[i]

        cf = checked(f)
        assert float(cf(jnp.arange(4.0), 2)) == 2.0
        with pytest.raises(Exception):
            cf(jnp.arange(4.0), 17)

    def test_assert_tree_finite(self):
        ok = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
        assert_tree_finite(ok)
        bad = {"a": jnp.ones(3), "b": {"c": jnp.array([1.0, jnp.nan])}}
        with pytest.raises(FloatingPointError, match="b.*c"):
            assert_tree_finite(bad, "state")

    def test_profile_trace_writes(self, tmp_path):
        with profile_trace(str(tmp_path)):
            jax.block_until_ready(jnp.ones(8) * 2)
        # trace directory materialized with at least one event file
        found = any(f for _, _, fs in os.walk(tmp_path) for f in fs)
        assert found


# -------------------------------------------------------------------------
# mock API server for the k8s client


class _MockK8s(ThreadingHTTPServer):
    def __init__(self):
        self.store: dict[str, dict] = {}
        self.power: list[tuple[str, str]] = []
        super().__init__(("127.0.0.1", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, status, obj):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        store = self.server.store
        if self.path.endswith("/virtualservers"):
            self._reply(200, {"items": list(store.values())})
        elif self.path in store:
            self._reply(200, store[self.path])
        else:
            self._reply(404, {"message": "not found"})

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        manifest = json.loads(self.rfile.read(n))
        name = manifest["metadata"]["name"]
        key = f"{self.path}/{name}"
        # simulate the controller: ready with an IP on creation
        manifest["status"] = {
            "conditions": [{"type": "VirtualServerReady",
                            "status": "True", "reason": "Running"}],
            "network": {"internalIP": "10.0.0.7"},
        }
        self.server.store[key] = manifest
        self._reply(201, manifest)

    def do_DELETE(self):
        if self.server.store.pop(self.path, None) is not None:
            self._reply(200, {"status": "Success"})
        else:
            self._reply(404, {"message": "not found"})

    def do_PUT(self):
        parts = self.path.rsplit("/", 2)
        self.server.power.append((parts[-2], parts[-1]))
        self._reply(202, {"status": "ok"})

    def log_message(self, fmt, *args):
        pass


@pytest.fixture
def mock_k8s():
    server = _MockK8s()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server
    server.shutdown()


class TestVirtualServerClient:
    def _client(self, server):
        port = server.server_address[1]
        k8s = K8sClient(api_server=f"http://127.0.0.1:{port}", token="t")
        return VirtualServerClient(k8s, namespace="tenant-test")

    def test_crud_ready_ip(self, mock_k8s):
        vs = self._client(mock_k8s)
        manifest = {
            "apiVersion": "virtualservers.coreweave.com/v1alpha1",
            "kind": "VirtualServer",
            "metadata": {"name": "vs-test"},
            "spec": {"region": "ORD1"},
        }
        assert not vs.exists("vs-test")
        vs.create(manifest)
        assert vs.exists("vs-test")
        ready = vs.wait_ready("vs-test", timeout=2, poll=0.05)
        assert ready["status"]["conditions"][0]["status"] == "True"
        assert vs.get_ip("vs-test") == "10.0.0.7"
        assert [v["metadata"]["name"] for v in vs.list()] == ["vs-test"]
        vs.delete("vs-test")
        assert not vs.exists("vs-test")

    def test_power_subresources(self, mock_k8s):
        vs = self._client(mock_k8s)
        vs.start("vm-1")
        vs.stop("vm-1")
        assert mock_k8s.power == [("vm-1", "start"), ("vm-1", "stop")]

    def test_api_error_status(self, mock_k8s):
        vs = self._client(mock_k8s)
        with pytest.raises(ApiError) as ei:
            vs.get("missing")
        assert ei.value.status == 404


class TestRequestRetries:
    """Transient-failure retry in the shared request path (5xx/429 and
    connection errors back off and re-attempt; 4xx surface immediately)."""

    def _client(self, monkeypatch, responses, retries=3):
        import io
        import urllib.error

        from kubernetes_cloud_tpu.deploy import k8s_client as mod

        calls = []
        sleeps = []

        def fake_urlopen(req, context=None, timeout=None):
            calls.append(req.full_url)
            outcome = responses[min(len(calls) - 1, len(responses) - 1)]
            if isinstance(outcome, int):
                raise urllib.error.HTTPError(
                    req.full_url, outcome, "err", {}, io.BytesIO(b"boom"))
            if isinstance(outcome, Exception):
                raise outcome

            class _Resp:
                def read(self):
                    return json.dumps(outcome).encode()

                def __enter__(self):
                    return self

                def __exit__(self, *a):
                    return False

            return _Resp()

        monkeypatch.setattr(mod.urllib.request, "urlopen", fake_urlopen)
        monkeypatch.setattr(mod.time, "sleep", sleeps.append)
        client = K8sClient(api_server="http://api", token="t",
                           retries=retries, backoff=0.5)
        return client, calls, sleeps

    def test_5xx_then_success(self, monkeypatch):
        client, calls, sleeps = self._client(
            monkeypatch, [503, 502, {"ok": True}])
        assert client.get("/api/v1/x") == {"ok": True}
        assert len(calls) == 3
        # exponential: base*2^0, base*2^1 (plus jitter <= 25%)
        assert 0.5 <= sleeps[0] <= 0.625 and 1.0 <= sleeps[1] <= 1.25

    def test_connection_error_retried(self, monkeypatch):
        import urllib.error

        client, calls, _ = self._client(
            monkeypatch,
            [urllib.error.URLError("refused"), {"ok": 1}])
        assert client.get("/x") == {"ok": 1}
        assert len(calls) == 2

    def test_4xx_not_retried(self, monkeypatch):
        client, calls, sleeps = self._client(monkeypatch, [404])
        with pytest.raises(ApiError) as ei:
            client.get("/x")
        assert ei.value.status == 404
        assert len(calls) == 1 and not sleeps

    def test_exhaustion_raises_last_error(self, monkeypatch):
        client, calls, sleeps = self._client(
            monkeypatch, [500, 500, 500], retries=2)
        with pytest.raises(ApiError) as ei:
            client.get("/x")
        assert ei.value.status == 500
        assert len(calls) == 3 and len(sleeps) == 2

    def test_retries_disabled(self, monkeypatch):
        client, calls, _ = self._client(monkeypatch, [503], retries=0)
        with pytest.raises(ApiError):
            client.get("/x")
        assert len(calls) == 1

    def test_post_not_replayed(self, monkeypatch):
        """POST is not idempotent: neither a lost response nor a gateway
        5xx (which may follow a successful apply) is blindly re-sent — the
        Job executor owns the 409 follow-up.  Only 429 (never admitted)
        retries a create."""
        import urllib.error

        client, calls, _ = self._client(
            monkeypatch,
            [urllib.error.URLError("reset"), {"ok": 1}])
        with pytest.raises(urllib.error.URLError):
            client.create("/x", {"metadata": {"name": "j"}})
        assert len(calls) == 1

        client2, calls2, _ = self._client(monkeypatch, [504, {"ok": 1}])
        with pytest.raises(ApiError):
            client2.create("/x", {"metadata": {"name": "j"}})
        assert len(calls2) == 1

        client3, calls3, _ = self._client(monkeypatch, [429, {"ok": 1}])
        assert client3.create("/x", {"metadata": {"name": "j"}}) == {"ok": 1}
        assert len(calls3) == 2
