"""Debug checks (checkify/finite guards), profiler hooks, k8s/VirtualServer
clients (reference ``virtual-server/examples/python``)."""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu.core.debug import (
    assert_tree_finite,
    checked,
    profile_trace,
)
from kubernetes_cloud_tpu.deploy.k8s_client import ApiError, K8sClient
from kubernetes_cloud_tpu.deploy.vsclient import VirtualServerClient


class TestChecked:
    def test_nan_raises(self):
        def f(x):
            return jnp.log(x)

        cf = checked(f)  # checked() jits internally
        cf(jnp.ones(3))  # fine
        with pytest.raises(Exception, match="nan"):
            cf(-jnp.ones(3))

    def test_oob_raises(self):
        def f(x, i):
            return x[i]

        cf = checked(f)
        assert float(cf(jnp.arange(4.0), 2)) == 2.0
        with pytest.raises(Exception):
            cf(jnp.arange(4.0), 17)

    def test_assert_tree_finite(self):
        ok = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
        assert_tree_finite(ok)
        bad = {"a": jnp.ones(3), "b": {"c": jnp.array([1.0, jnp.nan])}}
        with pytest.raises(FloatingPointError, match="b.*c"):
            assert_tree_finite(bad, "state")

    def test_profile_trace_writes(self, tmp_path):
        with profile_trace(str(tmp_path)):
            jax.block_until_ready(jnp.ones(8) * 2)
        # trace directory materialized with at least one event file
        found = any(f for _, _, fs in os.walk(tmp_path) for f in fs)
        assert found


# -------------------------------------------------------------------------
# mock API server for the k8s client


class _MockK8s(ThreadingHTTPServer):
    def __init__(self):
        self.store: dict[str, dict] = {}
        self.power: list[tuple[str, str]] = []
        super().__init__(("127.0.0.1", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, status, obj):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        store = self.server.store
        if self.path.endswith("/virtualservers"):
            self._reply(200, {"items": list(store.values())})
        elif self.path in store:
            self._reply(200, store[self.path])
        else:
            self._reply(404, {"message": "not found"})

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        manifest = json.loads(self.rfile.read(n))
        name = manifest["metadata"]["name"]
        key = f"{self.path}/{name}"
        # simulate the controller: ready with an IP on creation
        manifest["status"] = {
            "conditions": [{"type": "VirtualServerReady",
                            "status": "True", "reason": "Running"}],
            "network": {"internalIP": "10.0.0.7"},
        }
        self.server.store[key] = manifest
        self._reply(201, manifest)

    def do_DELETE(self):
        if self.server.store.pop(self.path, None) is not None:
            self._reply(200, {"status": "Success"})
        else:
            self._reply(404, {"message": "not found"})

    def do_PUT(self):
        parts = self.path.rsplit("/", 2)
        self.server.power.append((parts[-2], parts[-1]))
        self._reply(202, {"status": "ok"})

    def log_message(self, fmt, *args):
        pass


@pytest.fixture
def mock_k8s():
    server = _MockK8s()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server
    server.shutdown()


class TestVirtualServerClient:
    def _client(self, server):
        port = server.server_address[1]
        k8s = K8sClient(api_server=f"http://127.0.0.1:{port}", token="t")
        return VirtualServerClient(k8s, namespace="tenant-test")

    def test_crud_ready_ip(self, mock_k8s):
        vs = self._client(mock_k8s)
        manifest = {
            "apiVersion": "virtualservers.coreweave.com/v1alpha1",
            "kind": "VirtualServer",
            "metadata": {"name": "vs-test"},
            "spec": {"region": "ORD1"},
        }
        assert not vs.exists("vs-test")
        vs.create(manifest)
        assert vs.exists("vs-test")
        ready = vs.wait_ready("vs-test", timeout=2, poll=0.05)
        assert ready["status"]["conditions"][0]["status"] == "True"
        assert vs.get_ip("vs-test") == "10.0.0.7"
        assert [v["metadata"]["name"] for v in vs.list()] == ["vs-test"]
        vs.delete("vs-test")
        assert not vs.exists("vs-test")

    def test_power_subresources(self, mock_k8s):
        vs = self._client(mock_k8s)
        vs.start("vm-1")
        vs.stop("vm-1")
        assert mock_k8s.power == [("vm-1", "start"), ("vm-1", "stop")]

    def test_api_error_status(self, mock_k8s):
        vs = self._client(mock_k8s)
        with pytest.raises(ApiError) as ei:
            vs.get("missing")
        assert ei.value.status == 404
