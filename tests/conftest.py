"""Test fixtures: an 8-device CPU-simulated mesh.

Multi-device behavior (sharding, collectives, pjit) is tested without real
TPU hardware via ``--xla_force_host_platform_device_count=8`` — the
JAX-native fake backend (SURVEY.md §4).  The flag must be set before jax
initializes its backends, hence the module-level env mutation.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import pytest  # noqa: E402

# Some environments pin the default platform to a real accelerator
# regardless of JAX_PLATFORMS (e.g. the axon TPU shim).  Tests must run on
# the 8-device CPU simulation with full fp32 matmul precision, so force the
# default device to CPU; meshes are built from jax.devices("cpu") anyway.
if jax.default_backend() != "cpu":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs[:n]


@pytest.fixture
def devices8():
    return cpu_devices(8)
