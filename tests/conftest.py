"""Test fixtures: an 8-device CPU-simulated mesh.

Multi-device behavior (sharding, collectives, pjit) is tested without real
TPU hardware via ``--xla_force_host_platform_device_count=8`` — the
JAX-native fake backend (SURVEY.md §4).  The flag must be set before jax
initializes its backends, hence the module-level env mutation.
"""

import os
import pathlib
import sys

# cwd-independence: the package imports and the slow/quick lane matching
# below must work no matter where pytest was invoked from.
_REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The full suite JIT-compiles O(1000) XLA programs in ONE process, and
# on this backend each CPU executable holds tens of mmap regions for
# its lifetime (jit caches are deliberately process-global, so they
# are never released).  Past the kernel's default vm.max_map_count
# (65 530) an mmap inside XLA's compiler fails and the process dies
# with a bare SIGSEGV — measured: the suite brushes ~63 k maps and the
# crash lands in whichever innocent test compiles next, which made it
# look like a test bug twice before the real cause was found.  Raise
# the ceiling when permitted (CI runs as root); silently keep the
# status quo otherwise.  The sysctl is machine-global, so restore the
# prior value at interpreter exit — a root pytest on a shared box must
# not leave a permanent kernel-limit change behind.  (A concurrent
# second session's raise can be clobbered by the first one's restore;
# rare enough to accept over leaking the limit.)
try:
    with open("/proc/sys/vm/max_map_count") as _f:
        _maps = int(_f.read())
    if _maps < 1_048_576:
        with open("/proc/sys/vm/max_map_count", "w") as _f:
            _f.write("1048576")

        import atexit

        def _restore_map_count(prev=_maps):
            try:
                with open("/proc/sys/vm/max_map_count", "w") as f:
                    f.write(str(prev))
            except OSError:
                pass

        atexit.register(_restore_map_count)
except (OSError, ValueError):  # not root / not Linux: best-effort only
    pass

import jax  # noqa: E402
import pytest  # noqa: E402

# Some environments pin the default platform to a real accelerator
# regardless of JAX_PLATFORMS (e.g. the axon TPU shim).  Tests must run on
# the 8-device CPU simulation with full fp32 matmul precision, so force the
# default device to CPU; meshes are built from jax.devices("cpu") anyway.
if jax.default_backend() != "cpu":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])


# ---------------------------------------------------------------------------
# quick / slow lanes: ``pytest -m quick`` gives a <5 min core signal on a
# 1-CPU box; ``-m slow`` runs the heavy end-to-end/chaos/parity tests.
# Measured on a 1-CPU runner; entries are tests >= ~10 s there.
# ---------------------------------------------------------------------------

SLOW_TESTS = {
    "tests/test_causal_lm.py::test_chunked_loss_matches_dense",
    "tests/test_causal_lm.py::test_remat_matches_no_remat",
    "tests/test_chaos.py::test_kill_and_resume",
    "tests/test_chaos.py::test_sigterm_graceful_checkpoint",
    "tests/test_data_tools.py::TestReplicatedService::test_multi_candidate_generation",
    "tests/test_diffusion.py::test_sd_dreambooth_prior_loss",
    "tests/test_diffusion.py::test_sd_service_roundtrip",
    "tests/test_diffusion.py::test_sd_train_loop_and_checkpoint",
    "tests/test_diffusion.py::test_sd_v_prediction_changes_target",
    "tests/test_entrypoints.py::test_classifier_service_roundtrip",
    "tests/test_entrypoints.py::test_sd_finetuner_cli_end_to_end",
    "tests/test_entrypoints.py::test_sd_serialize_entrypoint",
    "tests/test_finetuner_cli.py::test_evaluator_main",
    "tests/test_finetuner_cli.py::test_finetuner_main_end_to_end",
    "tests/test_hf_parity.py::test_gpt_neox_parity",
    "tests/test_moe.py::test_moe_grad_flows_to_router",
    "tests/test_moe.py::test_moe_lm_expert_parallel_train",
    "tests/test_multiprocess.py::test_two_process_training",
    "tests/test_pipeline.py::test_pipeline_composed_with_moe",
    "tests/test_pipeline.py::test_pipeline_composed_with_seq_parallel",
    "tests/test_pipeline.py::test_pipeline_grad_matches_dense",
    "tests/test_pipeline.py::test_pipeline_train_step",
    "tests/test_resnet.py::test_bottleneck_param_count_resnet50",
    "tests/test_resnet.py::test_forward_shapes_and_dtype",
    "tests/test_resnet.py::test_synthetic_learning_and_eval",
    "tests/test_ring_attention.py::test_ring_gqa",
    "tests/test_seq_parallel.py::test_seq_parallel_remat",
    "tests/test_seq_parallel.py::test_seq_parallel_train_step_matches_dense",
    "tests/test_tp_serving.py::test_tp_matches_single_device",
    "tests/test_train_step.py::test_loss_decreases_single_device",
    "tests/test_train_step.py::test_sharded_training_matches_single_device",
    "tests/test_trainer.py::test_fused_single_gas",
    "tests/test_trainer.py::test_prompt_sampling",
    "tests/test_trainer.py::test_resume_from_checkpoint",
    "tests/test_trainer.py::test_train_end_to_end",
    # round-5 additions (>= ~5 s on the 1-CPU runner): keeps the default
    # quick lane near the 2-minute target
    "tests/test_resnet.py::test_train_mode_updates_stats",
    "tests/test_hf_parity.py::test_gpt_neox_serial_residual_parity",
    "tests/test_generate.py::test_greedy_generate_matches_iterated_forward",
    "tests/test_generate.py::test_eos_stops_row",
    "tests/test_tp_serving.py::test_tp_gptj_style_config",
    "tests/test_tp_serving.py::test_tp_sharded_stream_load",
    "tests/test_pipeline.py::test_pipeline_forward_matches_dense",
    "tests/test_causal_lm.py::test_cast_once_matches_per_use_cast",
    "tests/test_ring_attention.py::test_ring_matches_dense_causal",
    "tests/test_ring_attention.py::test_ring_under_jit_grad",
    "tests/test_moe.py::test_moe_matches_per_token_reference",
    "tests/test_train_step.py::test_opt_state_is_sharded",
    # workflow orchestrator: the unit/chaos suites (test_workflow.py,
    # test_workflow_chaos.py) are jax-free and stay in the quick tier-1
    # lane; only the full canned-pipeline run (download → tokenize →
    # train → serve, minutes of subprocess work) is slow
    "tests/test_workflow_e2e.py::test_finetune_and_serve_end_to_end",
}


# Matching keys on the repo-root-relative file path (not the nodeid, which
# drops the "tests/" prefix when pytest runs from inside tests/; not the
# basename, which would collide with same-named files in subdirectories).
_REPO_PATH = pathlib.Path(_REPO_ROOT)


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        try:
            rel = item.path.relative_to(_REPO_PATH).as_posix()
        except ValueError:  # collected from outside the repo
            rel = item.path.name
        key = rel + "::" + base.split("::", 1)[-1]
        if key in SLOW_TESTS or item.get_closest_marker("slow"):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.quick)

    # The quick lane is the default: a bare ``pytest`` run executes only
    # it (~2 min on 1 CPU), so the gate actually gets run.  The slow
    # multi-process/parity/e2e suites run with ``-m slow`` (or
    # ``-m "slow or quick"`` / KCT_FULL_TESTS=1 for everything — CI's
    # full lane).
    # Explicitly named tests or files bypass the lane filter — whoever
    # types a node id or .py path means to run exactly that.
    explicit = any("::" in a or a.endswith(".py") for a in config.args)
    if (not config.getoption("-m") and not config.getoption("keyword")
            and not explicit
            and not os.environ.get("KCT_FULL_TESTS")):
        selected = [i for i in items if not i.get_closest_marker("slow")]
        if len(selected) != len(items):
            config.hook.pytest_deselected(
                items=[i for i in items if i.get_closest_marker("slow")])
            items[:] = selected


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs[:n]


@pytest.fixture
def devices8():
    return cpu_devices(8)
