"""Region-scale simulator: determinism, the tier-1 smoke scenario
(seeded, virtual-clock, sub-5-seconds), the three-arm acceptance
comparison the ISSUE pins (autoscaled beats fixed-min AND fixed-peak
on cost-normalized goodput with zero drops), scale-from-zero through
the simulated activator, independent prefill/decode pool sizing, and
the multi-hour region runs (``slow`` lane).  Pure Python — no jax, no
threads, no wall clock inside the sim."""

import pytest

from kubernetes_cloud_tpu.serve.autoscaler import (
    AutoscalerConfig,
    RolePolicy,
)
from kubernetes_cloud_tpu.serve.simulate import (
    FlashCrowd,
    ReplicaModel,
    SimConfig,
    VirtualClock,
    WorkloadConfig,
    compare_fleets,
    default_autoscaler_cfg,
    flash_crowd_workload,
    peak_replicas,
    run_scenario,
)
from kubernetes_cloud_tpu.serve.trace import thinning_arrivals, zipf_user


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def test_virtual_clock_is_monotonic():
    clk = VirtualClock()
    clk.advance_to(5.0)
    assert clk.now() == 5.0
    with pytest.raises(ValueError):
        clk.advance_to(4.0)


def test_flash_crowd_ramp_shape():
    fc = FlashCrowd(at_s=100.0, duration_s=60.0, multiplier=5.0,
                    ramp_s=10.0)
    assert fc.multiplier_at(50.0) == 1.0
    assert fc.multiplier_at(100.0) == 1.0  # ramp start
    assert fc.multiplier_at(105.0) == pytest.approx(3.0)  # mid-ramp
    assert fc.multiplier_at(130.0) == 5.0  # plateau
    assert fc.multiplier_at(155.0) == pytest.approx(3.0)  # ramp-down
    assert fc.multiplier_at(161.0) == 1.0
    with pytest.raises(ValueError):
        FlashCrowd(at_s=0.0, duration_s=10.0, ramp_s=6.0)  # ramps > fit
    with pytest.raises(ValueError):
        FlashCrowd(at_s=0.0, duration_s=10.0, multiplier=0.5)


def test_workload_rate_composes_diurnal_and_flash():
    wl = WorkloadConfig(duration_s=1000.0, base_rps=2.0,
                        diurnal_period_s=1000.0, diurnal_amplitude=0.5,
                        flash_crowds=(FlashCrowd(
                            at_s=100.0, duration_s=100.0,
                            multiplier=4.0, ramp_s=0.0),))
    assert wl.rate(0.0) == pytest.approx(2.0)
    assert wl.rate(250.0) == pytest.approx(3.0)  # diurnal peak
    assert wl.rate(150.0) == pytest.approx(
        4.0 * 2.0 * (1 + 0.5 * __import__("math").sin(
            2 * __import__("math").pi * 0.15)))
    assert wl.rate_max() >= wl.rate(150.0)
    with pytest.raises(ValueError):
        WorkloadConfig(duration_s=50.0, flash_crowds=(
            FlashCrowd(at_s=40.0, duration_s=30.0),))


def test_thinning_rejects_rate_above_envelope():
    import random
    with pytest.raises(ValueError):
        thinning_arrivals(random.Random(0), 10.0, lambda t: 5.0, 2.0)


def test_zipf_user_is_heavy_tailed_and_bounded():
    import random
    rng = random.Random(1)
    ranks = [zipf_user(rng, 1_000_000, 1.3) for _ in range(5000)]
    assert all(0 <= r < 1_000_000 for r in ranks)
    head = sum(1 for r in ranks if r == 0) / len(ranks)
    assert 0.1 < head < 0.35  # rank-0 mass for s=1.3
    assert max(ranks) > 10_000  # the tail is actually long
    with pytest.raises(ValueError):
        zipf_user(rng, 100, 1.0)


# ---------------------------------------------------------------------------
# the smoke scenario: seeded, virtual clock, fast
# ---------------------------------------------------------------------------

SMOKE_WL = flash_crowd_workload(duration_s=900.0, base_rps=3.0,
                                flash_at_s=300.0,
                                flash_duration_s=180.0,
                                flash_multiplier=8.0, seed=1)
SMOKE_SIM = SimConfig(tick_s=0.25)


def test_simulation_is_deterministic():
    a = run_scenario(SMOKE_WL, SMOKE_SIM, mode="autoscaled",
                     autoscaler_cfg=default_autoscaler_cfg())
    b = run_scenario(SMOKE_WL, SMOKE_SIM, mode="autoscaled",
                     autoscaler_cfg=default_autoscaler_cfg())
    assert a == b


def test_smoke_acceptance_three_arm_comparison():
    """The ISSUE acceptance criterion at smoke scale: on the
    flash-crowd trace the autoscaled fleet strictly beats the fixed
    minimal fleet (which drowns) AND the fixed peak fleet (which pays
    peak all day) on cost-normalized goodput, with zero drops."""
    out = compare_fleets(SMOKE_WL, SMOKE_SIM, min_fleet=1)
    auto, fmin, fpeak = (out["autoscaled"], out["fixed_min"],
                         out["fixed_peak"])
    assert out["autoscaled_zero_drops"] and auto["dropped"] == 0
    assert out["autoscaled_beats_min"] and out["autoscaled_beats_peak"]
    g = "cost_normalized_goodput"
    assert auto[g] > fmin[g] and auto[g] > fpeak[g]
    # the min arm loses by violating SLOs, the peak arm by burning
    # replica-seconds — each for its OWN reason
    assert fmin["slo_attainment"] < 0.5 < auto["slo_attainment"]
    assert fmin["slo_violation_minutes"] > auto["slo_violation_minutes"]
    assert fpeak["replica_seconds"] > 1.5 * auto["replica_seconds"]
    # the autoscaler reacted to the crowd and completed everything
    crowd = auto["flash_crowds"][0]
    assert crowd["reaction_s"] is not None and crowd["reaction_s"] < 60
    assert auto["completed"] == auto["requests"]
    assert auto["scale_ups"] > 0 and auto["scale_downs"] > 0


def test_scale_from_zero_completes_everything():
    wl = WorkloadConfig(duration_s=240.0, base_rps=2.0,
                        diurnal_amplitude=0.2, seed=7)
    cfg = default_autoscaler_cfg(min_replicas=0, max_replicas=8)
    r = run_scenario(wl, SimConfig(tick_s=0.25), mode="autoscaled",
                     autoscaler_cfg=cfg)
    assert r["dropped"] == 0
    assert r["completed"] == r["requests"] > 0
    assert r["scale_ups"] >= 1  # the pool really started at zero
    # the measured cold start replaced the configured prior
    cold = r["autoscaler"]["roles"]["colocated"]["cold_start_s"]
    assert cold != pytest.approx(10.0)


def test_disaggregated_pools_size_independently():
    """Prompt-heavy traffic must grow the prefill pool while decode
    stays small — DistServe's point, expressed by the control loop."""
    wl = WorkloadConfig(duration_s=300.0, base_rps=3.0,
                        prompt_tokens=(400, 800),
                        output_tokens=(4, 8), seed=3)
    cfg = AutoscalerConfig(
        tick_s=1.0, panic_threshold=1.5, scale_down_delay_s=60.0,
        roles={"prefill": RolePolicy(min_replicas=1, max_replicas=12,
                                     target_concurrency=2.0),
               "decode": RolePolicy(min_replicas=1, max_replicas=12,
                                    target_concurrency=2.0)})
    sim = SimConfig(tick_s=0.25, disaggregated=True,
                    replica=ReplicaModel(prefill_tps=600.0,
                                         decode_tps=40.0))
    r = run_scenario(wl, sim, mode="autoscaled", autoscaler_cfg=cfg)
    assert r["dropped"] == 0 and r["completed"] == r["requests"]
    roles = r["autoscaler"]["roles"]
    assert roles["prefill"]["desired"] > roles["decode"]["desired"]
    assert r["pools"]["prefill"]["final_alive"] \
        > r["pools"]["decode"]["final_alive"]

    # flipped shape: decode-heavy traffic grows the decode pool
    wl2 = WorkloadConfig(duration_s=300.0, base_rps=3.0,
                         prompt_tokens=(4, 8),
                         output_tokens=(200, 400), seed=3)
    r2 = run_scenario(wl2, sim, mode="autoscaled", autoscaler_cfg=cfg)
    roles2 = r2["autoscaler"]["roles"]
    assert roles2["decode"]["desired"] > roles2["prefill"]["desired"]


def test_fixed_mode_never_scales():
    r = run_scenario(SMOKE_WL, SMOKE_SIM, mode="fixed",
                     fixed_replicas={"colocated": 3})
    assert r["scale_ups"] == 0 and r["scale_downs"] == 0
    assert "autoscaler" not in r
    with pytest.raises(ValueError):
        run_scenario(SMOKE_WL, SMOKE_SIM, mode="fixed")
    with pytest.raises(ValueError):
        run_scenario(SMOKE_WL, SMOKE_SIM, mode="nonsense")


def test_peak_replicas_littles_law():
    wl = WorkloadConfig(duration_s=100.0, base_rps=10.0,
                        diurnal_amplitude=0.0,
                        prompt_tokens=(100, 100),
                        output_tokens=(40, 40))
    sim = SimConfig(replica=ReplicaModel(prefill_tps=1000.0,
                                         decode_tps=40.0))
    # service = 0.1 + 1.0 s; concurrency = 10 * 1.1 = 11; /3 -> 4
    assert peak_replicas(wl, sim, target_concurrency=3.0) == 4


def test_report_counts_unfinished_overload_honestly():
    # a hopeless fixed fleet: work queued at the horizon is reported
    # as unfinished, not silently dropped from the denominator
    wl = WorkloadConfig(duration_s=120.0, base_rps=8.0, seed=2)
    sim = SimConfig(tick_s=0.25, drain_grace_s=5.0)
    r = run_scenario(wl, sim, mode="fixed",
                     fixed_replicas={"colocated": 1})
    assert r["unfinished"] > 0
    assert r["requests"] == r["completed"] + r["dropped"] \
        + r["unfinished"]


# ---------------------------------------------------------------------------
# region scale (slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_region_scale_two_hour_day_with_flash_crowds():
    """Two simulated hours of diurnal region traffic with two flash
    crowds over a million-user Zipf population: the acceptance
    comparison must hold at scale, not just at smoke scale."""
    wl = WorkloadConfig(
        duration_s=7200.0, base_rps=12.0, diurnal_period_s=7200.0,
        diurnal_amplitude=0.6, n_users=2_000_000, zipf_s=1.3,
        flash_crowds=(
            FlashCrowd(at_s=1800.0, duration_s=300.0, multiplier=6.0,
                       ramp_s=30.0),
            FlashCrowd(at_s=5000.0, duration_s=240.0, multiplier=9.0,
                       ramp_s=20.0),
        ), seed=11)
    sim = SimConfig(tick_s=0.5)
    cfg = default_autoscaler_cfg(max_replicas=48,
                                 target_concurrency=3.0)
    out = compare_fleets(wl, sim, autoscaler_cfg=cfg, min_fleet=2)
    auto = out["autoscaled"]
    assert out["autoscaled_zero_drops"]
    assert out["autoscaled_beats_min"] and out["autoscaled_beats_peak"]
    assert auto["completed"] == auto["requests"]
    assert auto["slo_attainment"] > 0.95
    assert auto["users"] > 5_000  # the Zipf tail really was sampled
    for crowd in auto["flash_crowds"]:
        assert crowd["reaction_s"] is not None
        assert crowd["reaction_s"] < 120.0


@pytest.mark.slow
def test_region_scale_to_zero_overnight():
    """An overnight lull (rate ~0 for a long stretch) drains the pool
    to zero and the morning traffic cold-starts it back — no drops."""
    wl = WorkloadConfig(duration_s=3600.0, base_rps=1.0,
                        diurnal_period_s=3600.0,
                        diurnal_amplitude=0.95, seed=5)
    cfg = default_autoscaler_cfg(min_replicas=0, max_replicas=12)
    r = run_scenario(wl, SimConfig(tick_s=0.5), mode="autoscaled",
                     autoscaler_cfg=cfg)
    assert r["dropped"] == 0
    assert r["completed"] == r["requests"]
    # the pool really collapsed at some point: more ups than one
    # initial ramp implies at least one restart-from-drained
    assert r["scale_downs"] >= 1 and r["scale_ups"] >= 2
