"""Transformer sidecars, BPE codec, load-test harness, custom predictors
(reference ``online-inference/gpt-2``, ``image-classifier``,
``custom-sentiment``, ``custom-basnet``, ``tensorizer-isvc/benchmark``)."""

import base64
import io
import json

import numpy as np
import pytest

from kubernetes_cloud_tpu.serve.bpe import BPECodec, bytes_to_unicode
from kubernetes_cloud_tpu.serve.load_test import (
    run_concurrent,
    run_ramp,
    run_sync,
)
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.server import ModelServer


def make_codec(merges=()):
    b2u = bytes_to_unicode()
    vocab = {ch: i for i, ch in enumerate(sorted(b2u.values()))}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    return BPECodec(vocab, list(merges))


class TestBPE:
    def test_roundtrip_bytes_only(self):
        codec = make_codec()
        for text in ("hello world", "naïve café ☕", "  spaces\n\ttabs",
                     "123 mixed UPPER'case", "snake_case_ids", "__dunder__",
                     "# ## ### markdown", "a_b"):
            assert codec.decode(codec.encode(text)) == text

    def test_merges_reduce_length(self):
        plain = make_codec()
        merged = make_codec(merges=[("h", "e"), ("l", "l"), ("he", "ll")])
        text = "hello hello"
        ids_plain = plain.encode(text)
        ids_merged = merged.encode(text)
        assert len(ids_merged) < len(ids_plain)
        assert merged.decode(ids_merged) == text

    def test_from_dir(self, tmp_path):
        b2u = bytes_to_unicode()
        vocab = {ch: i for i, ch in enumerate(sorted(b2u.values()))}
        vocab["he"] = len(vocab)
        vocab["##"] = len(vocab)
        (tmp_path / "vocab.json").write_text(json.dumps(vocab))
        # merge rules whose first symbol is '#' are REAL rules, not
        # comments; only the #version header is skipped
        (tmp_path / "merges.txt").write_text("#version: 0.2\nh e\n# #\n")
        codec = BPECodec.from_dir(str(tmp_path))
        assert codec.decode(codec.encode("hey")) == "hey"
        assert len(codec.encode("he")) == 1
        assert len(codec.encode("##")) == 1


class EchoPredictor(Model):
    """Predictor standing in for the model container behind a sidecar."""

    def predict(self, payload):
        return {"predictions": payload.get("instances", [])}


class ArgmaxPredictor(Model):
    def predict(self, payload):
        return {"predictions": [
            [0.1, 0.9] if np.mean(inst) > 0 else [0.9, 0.1]
            for inst in payload.get("instances", [])]}


@pytest.fixture
def echo_server():
    server = ModelServer([EchoPredictor("echo")], host="127.0.0.1", port=0)
    server.load_all()
    server.start()
    yield server
    server.stop()


class TestTransformerSidecar:
    def test_text_bpe_roundtrip_through_predictor(self, echo_server):
        from kubernetes_cloud_tpu.serve.transformer import TextBPETransformer

        sidecar = TextBPETransformer(
            "echo", f"127.0.0.1:{echo_server.port}", codec=make_codec())
        sidecar.load()
        out = sidecar.predict({"instances": ["hello world"]})
        assert out == {"predictions": ["hello world"]}

    def test_image_transformer_b64(self):
        from PIL import Image

        from kubernetes_cloud_tpu.serve.transformer import ImageTransformer

        server = ModelServer([ArgmaxPredictor("cls")], host="127.0.0.1",
                             port=0)
        server.load_all()
        server.start()
        try:
            sidecar = ImageTransformer(
                "cls", f"127.0.0.1:{server.port}", image_size=16,
                class_map={0: "dark", 1: "bright"})
            sidecar.load()
            buf = io.BytesIO()
            Image.new("RGB", (32, 32), (255, 255, 255)).save(buf, "PNG")
            payload = {"instances": [{"image_bytes": {
                "b64": base64.b64encode(buf.getvalue()).decode()}}]}
            out = sidecar.predict(payload)
            assert out["predictions"] == ["bright"]  # white image > mean 0
        finally:
            server.stop()


class TestLoadTest:
    def test_sync_and_concurrent_stats(self, echo_server):
        url = (f"http://127.0.0.1:{echo_server.port}"
               f"/v1/models/echo:predict")
        payloads = [json.dumps({"instances": [i]}).encode()
                    for i in range(12)]
        for summary in (run_sync(url, payloads),
                        run_concurrent(url, payloads, concurrency=4)):
            stats = summary.stats()
            assert stats["requests"] == 12
            assert stats["successful"] == 12
            assert stats["goodput_rps"] == stats["throughput_rps"]
            assert stats["latency_mean_s"] > 0

    def test_ramp_profile_stages(self, echo_server):
        """Locust-style ramp: one stats row per concurrency stage with
        percentiles (reference locustfile.py's ramping-user profile)."""
        url = (f"http://127.0.0.1:{echo_server.port}"
               f"/v1/models/echo:predict")
        payloads = [json.dumps({"instances": ["x"]}).encode()]
        out = run_ramp(url, payloads, stages=[1, 2], stage_duration=0.5)
        assert [s["concurrency"] for s in out["stages"]] == [1, 2]
        for stage in out["stages"]:
            assert stage["successful"] >= 1
            assert stage["latency_p50_s"] is not None
            assert stage["latency_p99_s"] >= stage["latency_p50_s"]

    def test_goodput_counts_failures(self, echo_server):
        url = (f"http://127.0.0.1:{echo_server.port}"
               f"/v1/models/missing:predict")
        stats = run_sync(url, [b"{}"] * 3).stats()
        assert stats["successful"] == 0
        assert stats["goodput_rps"] == 0


class TestSentiment:
    def test_train_save_load_predict(self, tmp_path):
        from kubernetes_cloud_tpu.serve.sentiment import (
            SentimentModel,
            train,
        )

        texts = ["great movie loved it", "wonderful fantastic acting",
                 "best film ever amazing", "terrible waste of time",
                 "awful boring mess", "worst film ever hated it"]
        labels = [1, 1, 1, 0, 0, 0]
        params = train(texts, labels)
        model = SentimentModel(artifact_dir=str(tmp_path))
        model.save(params)
        model.load()
        out = model.predict(
            {"instances": ["loved it wonderful", "boring terrible"]})
        assert out["predictions"][0]["label"] == "positive"
        assert out["predictions"][1]["label"] == "negative"
        assert 0.5 < out["predictions"][0]["score"] <= 1.0


class TestCutoutClient:
    def test_composite_alpha(self, tmp_path, echo_server):
        from PIL import Image

        from kubernetes_cloud_tpu.serve.clients import cutout

        class MaskPredictor(Model):
            def predict(self, payload):
                # constant half-transparent 8x8 mask as nested list
                return {"predictions": [np.full((8, 8), 0.5).tolist()]}

        server = ModelServer([MaskPredictor("basnet")], host="127.0.0.1",
                             port=0)
        server.load_all()
        server.start()
        try:
            src = tmp_path / "in.png"
            Image.new("RGB", (8, 8), (10, 200, 30)).save(src)
            out = cutout(
                f"http://127.0.0.1:{server.port}"
                "/v1/models/basnet:predict",
                str(src), str(tmp_path / "out.png"))
            img = Image.open(out)
            assert img.mode == "RGBA"
            assert img.getpixel((4, 4))[3] == 127  # 0.5 * 255
        finally:
            server.stop()
