"""Canned ``finetune-and-serve`` pipeline end-to-end on the CPU-simulated
mesh: download → tokenize → train → verify artifact → serve
smoke-test in one engine run
(the acceptance path for ``python -m kubernetes_cloud_tpu.workflow run
finetune-and-serve``)."""

import json
import os

import pytest

from kubernetes_cloud_tpu.workflow import WorkflowRun
from kubernetes_cloud_tpu.workflow.events import read_events, summarize
from kubernetes_cloud_tpu.workflow.pipelines import canned

pytestmark = pytest.mark.slow


def test_finetune_and_serve_end_to_end(tmp_path):
    spec = canned("finetune-and-serve")
    run = WorkflowRun(spec, str(tmp_path),
                      params={"workdir": str(tmp_path),
                              "docs": "4", "epochs": "1"})
    result = run.run()
    assert result["status"] == "succeeded", result
    assert result["steps"] == {
        "seed-corpus": "succeeded",
        "dataset-downloader": "succeeded",
        "tokenizer": "succeeded",
        "finetuner": "succeeded",
        "tensors-verify": "succeeded",
        "serve-smoke": "succeeded",
    }
    # every primitive's artifact contract held
    assert (tmp_path / "dataset" / ".ready.txt").exists()
    assert (tmp_path / "dataset.tokens").exists()
    run_dir = tmp_path / "results-finetune-local"
    assert (run_dir / "final" / "model.tensors").exists()
    assert (run_dir / ".ready.txt").exists()
    # the smoke step's stdout is a KServe V1 response
    smoke = json.loads(result["outputs"]["serve-smoke"])
    assert smoke["predictions"] and "generated_text" in smoke["predictions"][0]
    # step events cover the whole DAG with durations
    rollup = summarize(read_events(str(tmp_path / "events.jsonl")))
    assert set(rollup) == set(result["steps"])
    assert rollup["finetuner"]["duration"] > 0

    # second run over the same workdir: pure resume, nothing re-executes
    result2 = WorkflowRun(spec, str(tmp_path),
                          params={"workdir": str(tmp_path),
                                  "docs": "4", "epochs": "1"}).run()
    assert result2["status"] == "succeeded"
    events = read_events(str(tmp_path / "events.jsonl"))
    starts = [e for e in events if e["event"] == "step_start"]
    assert len(starts) == 6  # the first run's six, none added
