import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models import PRESETS, forward, init_params, loss_fn
from kubernetes_cloud_tpu.parallel import shard_batch, shard_params

CFG = PRESETS["test-tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def ids():
    return jax.random.randint(jax.random.key(1), (8, 16), 0, CFG.vocab_size)


def test_forward_shape_and_dtype(params, ids):
    logits = jax.jit(forward, static_argnums=0)(CFG, params, ids)
    assert logits.shape == (8, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality(params, ids):
    """Perturbing token t must not change logits before t."""
    f = jax.jit(forward, static_argnums=0)
    base = f(CFG, params, ids)
    ids2 = ids.at[:, 10].set((ids[:, 10] + 1) % CFG.vocab_size)
    pert = f(CFG, params, ids2)
    np.testing.assert_allclose(base[:, :10], pert[:, :10], atol=1e-5)
    assert not np.allclose(base[:, 10:], pert[:, 10:])


def test_initial_loss_near_uniform(params, ids):
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids)}
    loss, metrics = jax.jit(loss_fn, static_argnums=0)(CFG, params, batch)
    assert abs(float(loss) - np.log(CFG.vocab_size)) < 0.5
    assert int(metrics["tokens"]) == 8 * 15


def test_attention_mask_excludes_padding(params, ids):
    """Loss over a padded batch must equal loss over the unpadded rows."""
    mask = jnp.ones_like(ids).at[:, 12:].set(0)
    batch = {"input_ids": ids, "attention_mask": mask}
    _, metrics = jax.jit(loss_fn, static_argnums=0)(CFG, params, batch)
    assert int(metrics["tokens"]) == 8 * 11  # pairs fully inside the mask


@pytest.mark.parametrize("variant", ["bloom", "gpt2", "rmsnorm_gqa"])
def test_architecture_variants(variant, ids):
    overrides = {
        "bloom": dict(pos_emb="alibi", parallel_residual=False,
                      embed_layernorm=True, tie_embeddings=True),
        "gpt2": dict(pos_emb="learned", parallel_residual=False,
                     tie_embeddings=True),
        "rmsnorm_gqa": dict(norm="rmsnorm", use_bias=False, num_kv_heads=2),
    }[variant]
    cfg = dataclasses.replace(CFG, **overrides)
    p = init_params(cfg, jax.random.key(0))
    logits = jax.jit(forward, static_argnums=0)(cfg, p, ids)
    assert logits.shape == (8, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("policy", ["nothing", "attn_out", "attn_mlp"])
def test_remat_matches_no_remat(params, ids, policy):
    cfg_r = dataclasses.replace(CFG, remat=True, remat_policy=policy)
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids)}
    g1 = jax.jit(jax.grad(lambda p: loss_fn(CFG, p, batch)[0]))(params)
    g2 = jax.jit(jax.grad(lambda p: loss_fn(cfg_r, p, batch)[0]))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-2, atol=2e-3),
        g1, g2)


def test_cast_once_matches_per_use_cast(params, ids):
    """cast_once bulk-casts the exact leaves the block casts per use, so
    logits are bitwise-equal; norm scales and the MoE router stay fp32."""
    cfg_c = dataclasses.replace(CFG, cast_once=True)
    base = jax.jit(forward, static_argnums=0)(CFG, params, ids)
    cast = jax.jit(forward, static_argnums=0)(cfg_c, params, ids)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(cast))

    # MoE variant: router numerics must be unaffected (fp32-routed).
    cfg_m = dataclasses.replace(CFG, moe_experts=4)
    cfg_mc = dataclasses.replace(cfg_m, cast_once=True)
    pm = init_params(cfg_m, jax.random.key(0))
    got = jax.jit(forward, static_argnums=0)(cfg_mc, pm, ids)
    want = jax.jit(forward, static_argnums=0)(cfg_m, pm, ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sharded_matches_unsharded(devices8, params, ids):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2), devices=devices8)
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids)}
    loss, _ = jax.jit(loss_fn, static_argnums=0)(CFG, params, batch)
    sloss, _ = jax.jit(loss_fn, static_argnums=0)(
        CFG, shard_params(params, mesh), shard_batch(batch, mesh))
    np.testing.assert_allclose(float(loss), float(sloss), rtol=1e-3)


def test_chunked_loss_matches_dense():
    import dataclasses

    from kubernetes_cloud_tpu.models.causal_lm import (
        PRESETS,
        init_params,
        loss_fn,
    )

    cfg = PRESETS["test-tiny"]
    params = init_params(cfg, jax.random.key(0))
    rng = jax.random.key(1)
    ids = jax.random.randint(rng, (2, 32), 0, cfg.vocab_size, dtype=jnp.int32)
    mask = jnp.ones((2, 32), jnp.int32).at[0, 20:].set(0)
    batch = {"input_ids": ids, "attention_mask": mask}

    dense_loss, dense_m = loss_fn(cfg, params, batch)
    ccfg = dataclasses.replace(cfg, loss_chunk_size=8)
    chunk_loss, chunk_m = loss_fn(ccfg, params, batch)
    np.testing.assert_allclose(np.asarray(chunk_loss),
                               np.asarray(dense_loss), rtol=1e-5)
    assert int(chunk_m["tokens"]) == int(dense_m["tokens"])

    # grads agree to bf16 matmul/storage noise: chunk-shaped [B,C,D]@[D,V]
    # products tile differently than the full [B,S,D]@[D,V] one, and the
    # lse path stores logits in the compute dtype, so individual bf16
    # roundings differ slightly
    gd = jax.grad(lambda p: loss_fn(cfg, p, batch)[0])(params)
    gc = jax.grad(lambda p: loss_fn(ccfg, p, batch)[0])(params)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=1e-3)


def test_chunked_loss_requires_divisible_seq():
    import dataclasses

    import pytest

    from kubernetes_cloud_tpu.models.causal_lm import (
        PRESETS,
        init_params,
        loss_fn,
    )

    cfg = dataclasses.replace(PRESETS["test-tiny"], loss_chunk_size=7)
    params = init_params(cfg, jax.random.key(0))
    ids = jnp.ones((1, 32), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        loss_fn(cfg, params, {"input_ids": ids})
