"""Finetuner/evaluator CLI tests: reference flag parity + end-to-end run."""

import json
import os

import numpy as np
import pytest

from kubernetes_cloud_tpu.train import evaluator_cli, finetuner_cli


def test_reference_flags_parse(tmp_path):
    ds = tmp_path / "d.tokens"
    np.zeros((4, 8), np.uint16).tofile(str(ds))
    argv = [
        "--run-name", "r1", "--model", "test-tiny", "--dataset", str(ds),
        # dash and underscore spellings both work (DashParser parity)
        "--train_ratio", "0.8", "--warmup-ratio", "0.05",
        "--bs", "-1", "--gradients", "4", "--zero-stage", "2",
        "--no-resume", "--fp16", "true", "--no-shuffle", "false",
        "--prompt-every", "10", "--top-k", "40", "--top-p", "0.9",
        "--repetition-penalty", "1.2", "--local-rank", "0",
        "--log-level", "debug",
    ]
    args = finetuner_cli.build_parser().parse_args(argv)
    assert args.run_name == "r1"
    assert args.train_ratio == 0.8
    assert args.bs == -1
    assert args.resume is False          # --no-resume flips dest
    assert args.fp16 is True
    assert args.shuffle is True          # --no-shuffle false => keep shuffle
    assert args.zero_stage == 2
    assert args.log_level == "DEBUG"


def test_bad_flag_values_rejected(tmp_path):
    ds = tmp_path / "d.tokens"
    np.zeros((4, 8), np.uint16).tofile(str(ds))
    base = ["--run-name", "r", "--model", "m", "--dataset", str(ds)]
    parser = finetuner_cli.build_parser()
    with pytest.raises(SystemExit):
        parser.parse_args(base + ["--bs", "0"])
    with pytest.raises(SystemExit):
        parser.parse_args(base + ["--train-ratio", "1.5"])
    with pytest.raises(SystemExit):
        parser.parse_args(base + ["--dataset", "/does/not/exist"])


def test_mine_ds_config(tmp_path):
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps({
        "optimizer": {"type": "AdamW", "params": {
            "lr": 1e-4, "betas": [0.9, 0.95], "eps": 1e-6,
            "weight_decay": 0.1}},
        "zero_optimization": {"stage": 2},
    }))
    mined = finetuner_cli._mine_ds_config(str(path))
    assert mined == {"lr": 1e-4, "beta1": 0.9, "beta2": 0.95, "eps": 1e-6,
                     "weight_decay": 0.1, "zero_stage": 2}
    assert finetuner_cli._mine_ds_config("") == {}


def test_finetuner_main_end_to_end(tmp_path):
    rng = np.random.RandomState(1)
    ds = tmp_path / "d.tokens"
    rng.randint(2, 400, size=(64, 32)).astype(np.uint16).tofile(str(ds))
    rc = finetuner_cli.main([
        "--run-name", "cli-e2e", "--model", "test-tiny",
        "--dataset", str(ds), "--context-size", "32",
        "--mesh", "data=8", "--bs", "8", "--gradients", "1",
        "--epochs", "1", "--save-steps", "0",
        "--output-path", str(tmp_path), "--logs", str(tmp_path / "logs"),
    ])
    assert rc == 0
    run_dir = tmp_path / "results-cli-e2e"
    assert (run_dir / "final" / "model.tensors").exists()
    assert (run_dir / ".ready.txt").exists()


def test_evaluator_main(tmp_path, capsys):
    prompts = tmp_path / "p.txt"
    prompts.write_text("hi\n")
    rc = evaluator_cli.main([
        "--model", "test-tiny", "--prompt-file", str(prompts),
        "--prompt-tokens", "4", "--prompt-samples", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PROMPT: hi" in out and "RESPONSE:" in out
