"""Distributed-trace end-to-end lane: real engines behind real
front-ends, asserting the ISSUE's acceptance scenario — ONE trace tree
assembled at the router's ``/debug/trace/<id>`` across hedged dispatch
(loser cancelled), retries, the disagg KV handoff, and transplant —
plus tail-sampling retention, the worst-TTFT exemplar ride-along,
stdlib/native front-end parity at the door, the ``trace.export``
chaos containment contract, ``perf_report --trace``'s dominant-edge
attribution, and load_test's client-minted traceparent cross-check.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.obs import dtrace
from kubernetes_cloud_tpu.serve import load_test, native_server
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.disagg import build_disaggregated_engine
from kubernetes_cloud_tpu.serve.fleet import (
    FleetConfig,
    FleetRouter,
    LocalReplica,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.server import ModelServer

pytestmark = [pytest.mark.fleet]

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(autouse=True)
def _fresh_trace_store():
    """Every test gets a fresh span store with head sampling pinned ON
    (``decide`` deletes dropped traces — tests asserting span presence
    must not roll dice); a clean default store is left behind."""
    dtrace.reset(head_sample=1.0)
    yield
    dtrace.reset()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def service(params):
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    return svc


def make_fleet(service, n, fcfg, engine_kw=None):
    kw = {"slots": 2, "max_len": 96}
    kw.update(engine_kw or {})
    replicas = []
    for i in range(n):
        model = ContinuousBatchingModel("lm", service,
                                        EngineConfig(**kw))
        model.load()
        server = ModelServer([model], host="127.0.0.1", port=0)
        replicas.append(LocalReplica(f"r{i}", server, fcfg))
    router = FleetRouter(replicas, fcfg, host="127.0.0.1", port=0)
    return router, replicas


def warm_all(replicas):
    for r in replicas:
        eng = r.server.models["lm"].engine
        eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait()


def warm_http(replicas, prompt, max_new=2):
    """Compile the exact prompt-shape program on EVERY replica before
    a race-sensitive test: a first-hit XLA compile on one leg would
    decide hedge races by compiler luck, not dispatch order."""
    for r in replicas:
        status, _ = r.call(
            "POST", "/v1/models/lm:predict",
            json.dumps({"instances": [prompt],
                        "parameters": {"max_new_tokens": max_new,
                                       "temperature": 0.0}}).encode(),
            None)
        assert status == 200


def _predict(port, prompt, max_new, timeout=60, rid=None, headers=None):
    payload = {"instances": [prompt],
               "parameters": {"max_new_tokens": max_new,
                              "temperature": 0.0}}
    if rid:
        payload["request_id"] = rid
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps(payload).encode(), headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path, timeout=30):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_until(cond, timeout=15.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _by_name(spans, name):
    return [s for s in spans if s["name"] == name]


# -- the acceptance tree: hedge with a cancelled loser ----------------------

def test_hedged_request_assembles_one_tree(service, capsys):
    """Router -> hedged dispatch: the winning hedge leg and the
    cancelled primary leg are sibling ``dispatch`` spans under ONE
    root; the replica trees parent into their exact legs; the loser's
    span is closed (dur_s recorded, outcome=cancelled); the trace is
    tail-retained as ``hedged``; perf_report --trace renders the tree
    and names the dominant edge."""
    fcfg = FleetConfig(dispatch_timeout_s=30.0, hedge_after_s=0.05,
                       probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg,
                                  engine_kw={"slots": 1})
    warm_all(replicas)
    warm_http(replicas, "hedge me")
    router.start()
    try:
        # keep auto SLO keeps out of the way: retention must come from
        # the "hedged" reason alone
        dtrace.configure(ttft_target_s=None, inter_token_target_s=None,
                         head_sample=0.0)
        # r0's only slot is busy -> the request parks queued there and
        # the hedge fires onto r1, which wins
        blocker = replicas[0].server.models["lm"].engine.submit(
            [5, 6, 7], max_new_tokens=80, temperature=0.0)
        status, obj = _predict(router.port, "hedge me", 4)
        assert status == 200
        assert obj["fleet"]["hedged"] and obj["fleet"]["hedge_win"]
        tid = obj["trace_id"]

        # the loser's engine-side cancelled span lands asynchronously
        _wait_until(lambda: _by_name(dtrace.store().spans_for(tid)
                                     or [], "cancelled"),
                    what="loser's cancelled span")
        status, tree = _get(router.port, f"/debug/trace/{tid}")
        assert status == 200
        spans = tree["spans"]

        roots = [s for s in spans if s["name"] == "server"
                 and s.get("parent_id") is None]
        assert len(roots) == 1  # ONE tree, rooted at the router door
        root = roots[0]
        assert root["status"] == 200 and root["route"] == "predict"

        legs = _by_name(spans, "dispatch")
        assert {d["leg"] for d in legs} == {"primary", "hedge"}
        assert all(d["parent_id"] == root["span_id"] for d in legs)
        winner = next(d for d in legs if d["leg"] == "hedge")
        loser = next(d for d in legs if d["leg"] == "primary")
        assert winner["outcome"] == "win" and winner["replica"] == "r1"
        # hedge-loser cancellation CLOSED its span
        assert loser["outcome"] == "cancelled" and "dur_s" in loser
        assert winner["retry"] == 0 and loser["retry"] == 0

        # each replica's door span parents into its own leg, and the
        # winning engine's lifecycle parents into the replica door
        leg_ids = {d["span_id"] for d in legs}
        doors = [s for s in _by_name(spans, "server")
                 if s.get("parent_id") in leg_ids]
        assert doors, "replica server spans must parent into the legs"
        win_door = next(s for s in doors
                        if s["parent_id"] == winner["span_id"])
        for name in ("queued", "admitted", "first_token", "complete"):
            assert any(s["parent_id"] == win_door["span_id"]
                       for s in _by_name(spans, name)), name
        # the cancelled loser re-parents its engine spans into r0's door
        cancelled = _by_name(spans, "cancelled")
        assert cancelled and all(s["parent_id"] not in
                                 (win_door["span_id"],)
                                 for s in cancelled)

        # tail sampling: hedged traces are ALWAYS retained (head
        # sampling is pinned to 0 above, so retention is the reason)
        assert "hedged" in tree["keep"]
        assert "hedge_wait" in tree["analysis"]["edges"]
        assert tree["analysis"]["dominant"]
        assert tid in tree["tree"] or "server" in tree["tree"]

        # the index + worst-TTFT exemplars ride GET /debug/trace
        status, idx = _get(router.port, "/debug/trace")
        assert status == 200
        assert any(e["trace_id"] == tid for e in idx["traces"])
        assert any(e["trace_id"] == tid
                   for e in idx["exemplars"].get("ttft", []))

        # perf_report --trace against the live assembler
        from scripts.perf_report import main as perf_main
        url = f"http://127.0.0.1:{router.port}"
        assert perf_main(["--url", url, "--trace", tid]) == 0
        out = capsys.readouterr().out
        assert "dominant edge:" in out and "dispatch" in out
        assert perf_main(["--url", url, "--trace", tid, "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["analysis"]["dominant"] == \
            tree["analysis"]["dominant"]
        blocker.wait()
    finally:
        router.shutdown()


def test_retry_leg_recorded_with_error_outcome(service):
    """A mid-flight engine crash -> the router retries on the peer:
    the trace carries BOTH dispatch legs (the failed one closed with
    outcome=error, the winner tagged with its retry ordinal) and is
    tail-retained as ``retried``."""
    fcfg = FleetConfig(dispatch_timeout_s=30.0, probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg)
    warm_all(replicas)
    router.start()
    try:
        dtrace.configure(ttft_target_s=None, inter_token_target_s=None,
                         head_sample=0.0)
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", at=2, times=1)]))
        status, obj = _predict(router.port, "after the storm", 6)
        assert status == 200 and obj["fleet"]["retried_ok"]
        tid = obj["trace_id"]
        faults.uninstall()

        status, tree = _get(router.port, f"/debug/trace/{tid}")
        assert status == 200
        legs = sorted(_by_name(tree["spans"], "dispatch"),
                      key=lambda d: d["retry"])
        assert [d["outcome"] for d in legs] == ["error", "ok"]
        assert [d["retry"] for d in legs] == [0, 1]
        assert legs[0]["replica"] != legs[1]["replica"]
        assert "retried" in tree["keep"]
        assert tree["analysis"]["edges"].get(
            "retry_amplification", 0.0) > 0.0
    finally:
        faults.uninstall()
        router.shutdown()


# -- disagg: the KV handoff keeps the prefill-side trace --------------------

def test_disagg_adoption_keeps_prefill_trace(params):
    """Prefill-role -> decode-role adoption stays inside ONE trace:
    the extract/transfer/install spans and the decode-side lifecycle
    all parent into the context bound on the prefill door."""
    pair = build_disaggregated_engine(
        CFG, params, EngineConfig(slots=2, max_len=64, paged=True,
                                  page_size=8, role="prefill",
                                  decode_slices=1),
        eos_token_id=None, pad_token_id=0, mesh=None, name="pair")
    pair.start()
    try:
        ctx = dtrace.mint()
        dtrace.bind("rid-dis", ctx)
        req = pair.submit(list(range(1, 12)), max_new_tokens=5,
                          temperature=0.0, request_id="rid-dis")
        req.wait()
        assert req.error is None
        _wait_until(lambda: _by_name(dtrace.store().spans_for(
            ctx.trace_id) or [], "complete"), what="completion span")
        spans = dtrace.store().spans_for(ctx.trace_id)
        for name in ("kv_extract", "kv_transfer", "kv_install",
                     "first_token", "complete"):
            got = _by_name(spans, name)
            assert got, f"missing {name} span"
            # every hop bound back into the SAME prefill-door context
            assert all(s["trace_id"] == ctx.trace_id
                       and s["parent_id"] == ctx.span_id for s in got)
        assert _by_name(spans, "kv_extract")[0]["pages"] >= 1
    finally:
        dtrace.unbind("rid-dis")
        pair.stop()


# -- transplant keeps the trace and re-parents the requeue ------------------

def test_transplant_reparents_and_tail_retains(service):
    """A queued request transplanted off a draining replica finishes
    on the survivor with the SAME trace: the ``requeued`` span joins
    the tree and the trace is tail-retained as ``transplanted``."""
    fcfg = FleetConfig(dispatch_timeout_s=60.0, probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg,
                                  engine_kw={"slots": 1})
    warm_all(replicas)
    router.start()
    try:
        dtrace.configure(ttft_target_s=None, inter_token_target_s=None,
                         head_sample=0.0)
        # r0's slot busy -> the routed request parks in r0's queue
        blocker = replicas[0].server.models["lm"].engine.submit(
            [9, 8, 7], max_new_tokens=48, temperature=0.0)
        got = {}

        def call():
            got["resp"] = _predict(router.port, "move me", 4,
                                   rid="rid-tp")
        t = threading.Thread(target=call, daemon=True)
        t.start()
        _wait_until(lambda: replicas[0].request_phase("rid-tp")
                    == "queued", what="request to queue on r0")
        moved = router._transplant_from(replicas[0])
        assert moved == 1
        t.join(timeout=60)
        assert not t.is_alive()
        status, obj = got["resp"]
        assert status == 200
        tid = obj["trace_id"]
        status, tree = _get(router.port, f"/debug/trace/{tid}")
        assert status == 200
        assert "transplanted" in tree["keep"]
        requeued = _by_name(tree["spans"], "requeued")
        assert requeued, "transplant must record the requeued span"
        span_ids = {s["span_id"] for s in tree["spans"]}
        assert all(s["parent_id"] in span_ids for s in requeued)
        assert _by_name(tree["spans"], "complete")
        blocker.wait()
    finally:
        router.shutdown()


# -- door parity: stdlib vs native front-end --------------------------------

class Echo(Model):
    def predict(self, payload):
        return {"predictions": payload.get("instances", [])}


def _door_contract(port):
    """Same three assertions against either front-end: a client-minted
    Traceparent is joined and echoed; garbage mints (never a 400); an
    absent header mints too."""
    url = f"http://127.0.0.1:{port}/v1/models/echo:predict"

    def post(headers):
        req = urllib.request.Request(
            url, data=json.dumps({"instances": ["x"]}).encode(),
            headers={"Content-Type": "application/json", **headers})
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())

    ctx = dtrace.mint()
    status, obj = post({dtrace.TRACEPARENT_HEADER: ctx.wire()})
    assert status == 200 and obj["trace_id"] == ctx.trace_id
    spans = dtrace.store().spans_for(ctx.trace_id)
    assert spans and spans[0]["name"] == "server"
    assert spans[0]["parent_id"] == ctx.span_id  # joined, not re-rooted

    status, obj = post({dtrace.TRACEPARENT_HEADER: "total-!garbage!"})
    assert status == 200  # garbage mints, NEVER a 400
    assert obj["trace_id"] and obj["trace_id"] != ctx.trace_id

    status, obj = post({})
    assert status == 200 and obj["trace_id"]


def test_stdlib_door_joins_and_mints():
    server = ModelServer([Echo("echo")], host="127.0.0.1", port=0)
    server.load_all()
    server.start()
    try:
        _door_contract(server.port)
    finally:
        server.stop()


def test_native_door_joins_and_mints():
    """Front-end parity: the native C front-end's raw header block
    feeds the SAME door, so Traceparent join/mint/garbage behave
    identically."""
    assert native_server.available()
    server = native_server.NativeModelServer(
        [Echo("echo")], host="127.0.0.1", port=0)
    server.load_all()
    server.start()
    try:
        _door_contract(server.port)
    finally:
        server.stop()


def test_payload_traceparent_field_honored():
    """Headerless hops carry the context as a payload field; the door
    honors it and rewrites it to its own span."""
    server = ModelServer([Echo("echo")], host="127.0.0.1", port=0)
    server.load_all()
    ctx = dtrace.mint()
    status, obj = server._route(
        "POST", "/v1/models/echo:predict",
        json.dumps({"instances": ["x"],
                    "traceparent": ctx.wire()}).encode(), None)
    assert status == 200 and obj["trace_id"] == ctx.trace_id


# -- tail sampling end to end ------------------------------------------------

def test_tail_sampling_drops_boring_keeps_interesting(service):
    """head_sample=0: a plain request's trace is dropped at decide
    time (404 at the assembler), a hedged one is retained — the
    kct_trace decision counters account for both."""
    from kubernetes_cloud_tpu import obs

    fcfg = FleetConfig(dispatch_timeout_s=30.0, hedge_after_s=0.05,
                       probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg,
                                  engine_kw={"slots": 1})
    warm_all(replicas)
    warm_http(replicas, "keep me")
    router.start()
    try:
        dtrace.configure(head_sample=0.0, ttft_target_s=None,
                         inter_token_target_s=None)
        before = obs.render_text()
        status, boring = _predict(router.port, "plain sailing", 3)
        assert status == 200 and not boring["fleet"]["hedged"]
        status, _404 = _get(router.port,
                            f"/debug/trace/{boring['trace_id']}")
        assert status == 404  # dropped at the router's decide

        blocker = replicas[0].server.models["lm"].engine.submit(
            [4, 4, 4], max_new_tokens=80, temperature=0.0)
        status, hedged = _predict(router.port, "keep me", 3)
        assert status == 200 and hedged["fleet"]["hedged"]
        status, tree = _get(router.port,
                            f"/debug/trace/{hedged['trace_id']}")
        assert status == 200 and "hedged" in tree["keep"]

        after = obs.render_text()
        delta = lambda d: (obs.sample_value(  # noqa: E731
            obs.parse_text(after), "kct_trace_traces_total",
            {"decision": d}) or 0) - (obs.sample_value(
                obs.parse_text(before), "kct_trace_traces_total",
                {"decision": d}) or 0)
        assert delta("dropped") >= 1
        assert delta("kept_tail") >= 1
        blocker.wait()
    finally:
        router.shutdown()


# -- trace.export chaos containment -----------------------------------------

def test_trace_export_raise_contained():
    server = ModelServer([Echo("echo")], host="127.0.0.1", port=0)
    server.load_all()
    server.start()
    try:
        faults.install(faults.FaultInjector(
            [FaultSpec("trace.export", mode="raise", at=1, times=1)]))
        status, obj = _get(server.port, "/debug/trace")
        assert status == 500  # contained to THIS debug request
        # data plane and readiness never route through the export
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/models/echo:predict",
            data=json.dumps({"instances": ["x"]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert _get(server.port, "/healthz")[0] == 200
        # fault exhausted: the export recovers
        status, obj = _get(server.port, "/debug/trace")
        assert status == 200 and "traces" in obj
    finally:
        server.stop()


def test_trace_export_hang_parks_only_that_request():
    server = ModelServer([Echo("echo")], host="127.0.0.1", port=0)
    server.load_all()
    server.start()
    try:
        faults.install(faults.FaultInjector(
            [FaultSpec("trace.export", mode="hang", at=1, times=1,
                       delay_s=30.0)]))
        parked = {}

        def debug_call():
            parked["resp"] = _get(server.port, "/debug/trace",
                                  timeout=60)
        t = threading.Thread(target=debug_call, daemon=True)
        t.start()
        _wait_until(lambda: (faults.active() or object()) and
                    faults.active().hits("trace.export") >= 1,
                    what="export to park")
        # the wedged export holds ONLY its own thread
        t0 = time.monotonic()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/models/echo:predict",
            data=json.dumps({"instances": ["x"]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert _get(server.port, "/healthz")[0] == 200
        assert time.monotonic() - t0 < 5.0
        faults.uninstall()  # releases the parked export
        t.join(timeout=10)
        assert not t.is_alive()
        assert parked["resp"][0] == 200
    finally:
        faults.uninstall()
        server.stop()


# -- load_test: client-minted traceparent cross-check ------------------------

def test_load_test_minted_traces_echoed_and_worst_ttft(service):
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/v1/models/lm:predict"
        payloads = [json.dumps(
            {"instances": [f"load {i}"],
             "parameters": {"max_new_tokens": 3,
                            "temperature": 0.0}}).encode()
            for i in range(6)]
        summary = load_test.run_concurrent(url, payloads,
                                           concurrency=3,
                                           mint_trace=True)
        assert summary.n_ok == 6
        # every 2xx echoed exactly the trace id the client minted
        check = load_test.check_trace(summary.results)
        assert check == {"requests_2xx": 6, "missing_trace_id": 0,
                         "mismatched_trace_id": 0, "ok": True}
        ids = {r.trace_id for r in summary.results}
        assert len(ids) == 6  # a DISTINCT trace per request
        stats = summary.stats()
        worst = stats["worst_ttft"]
        assert 1 <= len(worst) <= 5
        assert all(w["trace_id"] in ids for w in worst)
        assert worst == sorted(worst, key=lambda w: -w["ttft_s"])
    finally:
        server.stop()
        model.stop()
