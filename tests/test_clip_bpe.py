"""CLIP BPE codec vs transformers.CLIPTokenizer on the same vocab files.

A miniature CLIP-style vocabulary (byte alphabet + ``</w>`` variants +
merge-built subwords + specials) is written to disk and loaded by both
implementations; ids must agree exactly, including specials framing,
max-length padding/truncation, cleanup, and lower-casing — transformers
is the arbiter of the published algorithm.
"""

import json

import pytest

from kubernetes_cloud_tpu.serve.clip_bpe import CLIPBPECodec, bytes_to_unicode

pytestmark = pytest.mark.slow  # transformers import is seconds


@pytest.fixture(scope="module")
def vocab_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("clip_tok")
    b2u = bytes_to_unicode()
    alphabet = sorted(set(b2u.values()))
    vocab: dict[str, int] = {}
    for ch in alphabet:
        vocab[ch] = len(vocab)
    for ch in alphabet:
        vocab[ch + "</w>"] = len(vocab)
    merges = [
        ("t", "h"), ("th", "e</w>"), ("a", "n"), ("an", "d</w>"),
        ("i", "n"), ("in", "g</w>"), ("t", "o</w>"), ("e", "r"),
        ("c", "a"), ("ca", "t</w>"), ("d", "o"), ("do", "g</w>"),
        ("s", "n"), ("sn", "o"), ("sno", "w</w>"), ("er", "</w>"),
    ]
    for a, b in merges:
        vocab[a + b] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    with open(d / "vocab.json", "w") as f:
        json.dump(vocab, f)
    with open(d / "merges.txt", "w") as f:
        f.write("#version: 0.2\n")
        for a, b in merges:
            f.write(f"{a} {b}\n")
    return str(d)


@pytest.fixture(scope="module")
def both(vocab_dir):
    from transformers import CLIPTokenizer

    ours = CLIPBPECodec.from_dir(vocab_dir)
    theirs = CLIPTokenizer(vocab_file=vocab_dir + "/vocab.json",
                           merges_file=vocab_dir + "/merges.txt")
    return ours, theirs


PROMPTS = [
    "the cat and the dog",
    "A Dog In THE Snow",          # lower-casing
    "snowing   to the   cat",     # whitespace collapse
    "cat, dog; snow!",            # punctuation splits
    "cats dogs snowcat",          # partial merges / unknown tails
    "er catered",
]


@pytest.mark.parametrize("text", PROMPTS)
def test_encode_matches_transformers(both, text):
    ours, theirs = both
    assert ours.encode(text) == theirs(text, add_special_tokens=False)[
        "input_ids"]


def test_framed_padded_batch_matches_transformers(both):
    ours, theirs = both
    want = theirs(PROMPTS, padding="max_length", truncation=True,
                  max_length=16)["input_ids"]
    assert ours.encode_batch(PROMPTS, length=16) == want


def test_decode_roundtrip(both):
    ours, _ = both
    ids = ours.encode_batch(["the cat and the dog"], length=16)[0]
    assert ours.decode(ids) == "the cat and the dog"


def test_decode_keeps_interior_pad_token(vocab_dir):
    """SD-2.x pads with '!', a real vocab token: decode must strip only
    *trailing* pads, not legitimate interior occurrences."""
    import json as _json

    with open(vocab_dir + "/vocab.json") as f:
        vocab = _json.load(f)
    if "!" not in vocab:
        vocab["!"] = len(vocab)
    if "!</w>" not in vocab:
        vocab["!</w>"] = len(vocab)
    merges = sorted(CLIPBPECodec.from_dir(vocab_dir).ranks,
                    key=CLIPBPECodec.from_dir(vocab_dir).ranks.get)
    codec = CLIPBPECodec(vocab, merges, pad_token="!")
    ids = codec.encode("cat ! dog")
    framed = [codec.sot] + ids + [codec.eot] + [codec.pad] * 4
    out = codec.decode(framed)
    assert "!" in out          # interior '!' survives
    assert not out.endswith("!")  # trailing pads stripped
