"""End-to-end sequence-parallel training: ring attention inside the jitted
train step over a (data=2, seq=4) mesh, vs. the same model without seq
parallelism — losses must match."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models.causal_lm import PRESETS, loss_fn
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)


def _batch(cfg, b=4, s=64):
    rng = jax.random.key(7)
    ids = jax.random.randint(rng, (b, s), 0, cfg.vocab_size, dtype=jnp.int32)
    return {"input_ids": ids,
            "attention_mask": jnp.ones((b, s), jnp.int32)}


def test_seq_parallel_train_step_matches_dense(devices8):
    base_cfg = PRESETS["test-tiny"]
    ring_cfg = dataclasses.replace(base_cfg, attn_impl="ring")
    train_cfg = TrainConfig(warmup_steps=2, total_steps=10)

    mesh = build_mesh(MeshSpec(data=2, seq=4), devices=devices8)
    batch = _batch(base_cfg)

    # Same init on both paths (attn_impl does not affect init).
    state = init_train_state(base_cfg, train_cfg, jax.random.key(0), mesh)
    dense_loss, _ = loss_fn(base_cfg, state["params"], batch)

    sharded = shard_batch(batch, mesh, shard_seq=True)
    step = jax.jit(make_train_step(ring_cfg, train_cfg, mesh=mesh))
    state2, metrics = step(state, sharded)
    np.testing.assert_allclose(float(metrics["loss"]), float(dense_loss),
                               rtol=2e-4)
    assert int(state2["step"]) == 1


def test_seq_parallel_remat(devices8):
    cfg = dataclasses.replace(PRESETS["test-tiny"], attn_impl="ring",
                              remat=True)
    train_cfg = TrainConfig(warmup_steps=2, total_steps=10)
    mesh = build_mesh(MeshSpec(data=1, seq=8), devices=devices8)
    state = init_train_state(cfg, train_cfg, jax.random.key(0), mesh)
    batch = shard_batch(_batch(cfg, b=2, s=64), mesh, shard_seq=True)
    step = jax.jit(make_train_step(cfg, train_cfg, mesh=mesh))
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
