"""Server lifecycle + error-mapping contract (no accelerator needed):
the typed-error → HTTP-status ladder, the honest /healthz vs /readyz
split, deadline header propagation, and the SIGTERM graceful drain —
the probe-and-drain behaviour `deploy/online-inference/` assumes."""

import json
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultInjector, FaultSpec
from kubernetes_cloud_tpu.serve import boot
from kubernetes_cloud_tpu.serve.batcher import BatcherConfig, BatchingModel
from kubernetes_cloud_tpu.serve.errors import (
    DeadlineExceededError,
    EngineRestartedError,
    QueueFullError,
    StreamTimeoutError,
)
from kubernetes_cloud_tpu.serve.load_test import run_sync
from kubernetes_cloud_tpu.serve.model import Model, request_deadline
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
)

pytestmark = pytest.mark.chaos


class ScriptedModel(Model):
    """Predictor whose behaviour the payload scripts: raise a named
    error, sleep, or echo — letting each status-mapping case drive the
    real HTTP path without a real model."""

    ERRORS = {
        "queue_full": QueueFullError("request queue full"),
        "deadline": DeadlineExceededError("deadline expired in queue"),
        "restarted": EngineRestartedError("engine restarted; retry"),
        "stream_timeout": StreamTimeoutError("no token within 1s; retry"),
        "bad_request": ValueError("payload needs instances"),
        "boom": RuntimeError("segfault-adjacent"),
    }

    def predict(self, payload):
        raise_key = payload.get("raise")
        if raise_key:
            raise self.ERRORS[raise_key]
        if payload.get("sleep"):
            time.sleep(float(payload["sleep"]))
        if payload.get("check_deadline"):
            deadline = request_deadline(payload)
            if deadline is not None and time.monotonic() > deadline:
                raise DeadlineExceededError("deadline expired before start")
        return {"predictions": [payload.get("echo", "ok")],
                "deadline_ms": payload.get("deadline_ms")}


@pytest.fixture
def server():
    srv = ModelServer([ScriptedModel("m")], host="127.0.0.1", port=0)
    srv.load_all()
    srv.start()
    yield srv
    srv.stop()


def _post(server, payload, headers=None, path="/v1/models/m:predict"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def _get(server, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _status(server, payload, headers=None):
    try:
        return _post(server, payload, headers)[0]
    except urllib.error.HTTPError as e:
        return e.code


class TestErrorMapping:
    def test_typed_errors_map_to_contract_statuses(self, server):
        # the full ladder: 400 / 503-retryable family / 504 / 500
        assert _status(server, {"raise": "bad_request"}) == 400
        assert _status(server, {"raise": "queue_full"}) == 503
        assert _status(server, {"raise": "restarted"}) == 503
        assert _status(server, {"raise": "stream_timeout"}) == 503
        assert _status(server, {"raise": "deadline"}) == 504
        assert _status(server, {"raise": "boom"}) == 500
        assert _status(server, {"echo": "fine"}) == 200

    def test_deadline_header_injected_into_payload(self, server):
        _, out = _post(server, {"echo": "x"},
                       headers={"X-Request-Deadline-Ms": "1500"})
        assert float(out["deadline_ms"]) == 1500.0
        # payload beats header (client set it explicitly)
        _, out = _post(server, {"echo": "x", "deadline_ms": 3},
                       headers={"X-Request-Deadline-Ms": "1500"})
        assert float(out["deadline_ms"]) == 3

    def test_expired_deadline_header_maps_504(self, server):
        assert _status(server, {"check_deadline": True},
                       headers={"X-Request-Deadline-Ms": "0"}) == 504


class TestHealthModel:
    def test_healthz_always_200_readyz_tracks_model_health(self, server):
        assert _get(server, "/healthz")[0] == 200
        code, body = _get(server, "/readyz")
        assert code == 200 and body["status"] == "ready"
        # model goes unhealthy: readyz flips, healthz must NOT — a sick
        # engine is the supervisor's problem, not a reason to kill the
        # pod holding the loaded weights
        server.models["m"].ready = False
        code, body = _get(server, "/readyz")
        assert code == 503 and body["status"] == "unready"
        assert body["models"]["m"]["ok"] is False
        assert _get(server, "/healthz")[0] == 200
        server.models["m"].ready = True
        assert _get(server, "/readyz")[0] == 200


class TestDrain:
    def test_drain_completes_inflight_then_rejects_new(self, server):
        results = {}

        def slow_call():
            results["slow"] = _post(server, {"sleep": 0.4, "echo": "done"})

        t = threading.Thread(target=slow_call)
        t.start()
        time.sleep(0.1)  # the slow request is in flight
        drained = {}

        def do_drain():
            drained.update(server.drain(timeout=10.0))

        d = threading.Thread(target=do_drain)
        d.start()
        time.sleep(0.05)  # drain flag is up, slow request still running
        assert _get(server, "/readyz")[0] == 503
        assert _status(server, {"echo": "rejected"}) == 503
        t.join(timeout=10)
        d.join(timeout=10)
        # the in-flight request completed despite the drain
        assert results["slow"][0] == 200
        assert results["slow"][1]["predictions"] == ["done"]
        assert drained["drained"] is True and drained["inflight"] == 0

    def test_sigterm_handler_triggers_drain(self):
        srv = ModelServer([ScriptedModel("m")], host="127.0.0.1", port=0)
        srv.load_all()
        srv.start()
        previous = signal.getsignal(signal.SIGTERM)
        try:
            assert boot.install_sigterm_drain(srv, drain_timeout=5.0)
            signal.raise_signal(signal.SIGTERM)
            deadline = time.monotonic() + 10
            while not srv._draining and time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._draining
            deadline = time.monotonic() + 10
            while srv._httpd is not None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._httpd is None  # listener closed after drain
        finally:
            signal.signal(signal.SIGTERM, previous)
            srv.stop()


class TestBatcherSupervision:
    """The watchdog covers the dynamic batcher's dispatcher thread too
    — same heartbeat/restart/health contract as the engine, no
    accelerator required."""

    def test_dispatcher_crash_detected_restarted_and_serving(self):
        m = BatchingModel("b", lambda insts, params: [x * 2 for x in insts])
        m.load()
        sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.02,
                                                 hang_timeout_s=5.0))
        sup.watch(m)
        sup.start()
        try:
            assert m.predict({"instances": [3]})["predictions"] == [6]
            assert m.health()["ok"] is True
            # kill the dispatcher the way a segfault-class failure
            # would: the loop's fault site sits outside its try
            faults.install(FaultInjector([FaultSpec("dispatch")]))
            deadline = time.monotonic() + 10
            while sup.stats["crashes"] == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sup.stats["crashes"] == 1
            assert sup.stats["restarts"] == 1
            # the replacement dispatcher serves the same queue
            assert m.predict({"instances": [5]})["predictions"] == [10]
            assert m.health()["ok"] is True
        finally:
            faults.uninstall()
            sup.stop()
            m.stop()

    def test_abandon_dispatcher_fails_everyone_and_blocks_stragglers(self):
        """Circuit-open shutdown: the executing batch, queued entries,
        and any predict racing the drain all fail retryably — nobody
        hangs on a queue no dispatcher will ever service again."""

        def slow_inner(insts, params):
            time.sleep(0.3)
            return list(insts)

        m = BatchingModel("b", slow_inner,
                          BatcherConfig(max_batch_size=1))
        m.load()
        codes = {}

        def call(key, inst):
            try:
                codes[key] = m.predict({"instances": [inst]})
            except Exception as e:  # noqa: BLE001
                codes[key] = e

        t1 = threading.Thread(target=call, args=("executing", 1))
        t1.start()
        time.sleep(0.05)  # t1's batch is running in the dispatcher
        t2 = threading.Thread(target=call, args=("queued", 2))
        t2.start()
        time.sleep(0.05)
        m.abandon_dispatcher(QueueFullError("circuit open"))
        t1.join(timeout=5)
        t2.join(timeout=5)
        assert isinstance(codes["executing"], QueueFullError)
        assert isinstance(codes["queued"], QueueFullError)
        assert m._stop.is_set()  # the straggler guards are armed
        with pytest.raises(RuntimeError, match="stopped"):
            m.predict({"instances": [3]})

    def test_batcher_sheds_expired_queued_request(self):
        def slow_inner(insts, params):
            time.sleep(0.3)
            return list(insts)

        m = BatchingModel("b", slow_inner,
                          BatcherConfig(max_batch_size=1))
        m.load()
        try:
            got = {}
            t = threading.Thread(target=lambda: got.update(
                out=m.predict({"instances": [1]})))
            t.start()
            time.sleep(0.05)  # the slow batch is executing
            # 50ms budget vs ~250ms left of the running batch: expired
            # by the time the dispatcher reaches it → shed, not run
            with pytest.raises(DeadlineExceededError,
                               match="expired in queue"):
                m.predict({"instances": [2], "deadline_ms": 50})
            t.join(timeout=10)
            assert got["out"]["predictions"] == [1]  # bystander fine
            assert m.stats["deadline_shed"] == 1
        finally:
            m.stop()


class TestLoadTestOutcomes:
    def test_outcome_breakdown_and_deadline_header(self, server):
        url = f"http://127.0.0.1:{server.port}/v1/models/m:predict"
        payloads = [json.dumps(p).encode() for p in (
            {"echo": "a"}, {"echo": "b"},
            {"raise": "queue_full"},
            {"raise": "deadline"},
            {"raise": "boom"},
            {"raise": "bad_request"},
        )]
        stats = run_sync(url, payloads).stats()
        assert stats["outcomes"] == {"2xx": 2, "503_shed": 1,
                                     "504_deadline": 1, "5xx": 1, "4xx": 1}
        # --deadline-ms plumbs the header through the harness
        stats = run_sync(url, [json.dumps(
            {"check_deadline": True}).encode()],
            headers={"X-Request-Deadline-Ms": "0"}).stats()
        assert stats["outcomes"] == {"504_deadline": 1}
