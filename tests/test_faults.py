"""Fault-injection framework unit tests: the chaos suites are only as
trustworthy as the injector's determinism (same schedule every run)."""

import threading
import time

import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultError, FaultInjector, FaultSpec

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarmed():
    """Never leak an armed injector into (or out of) a test."""
    faults.uninstall()
    yield
    faults.uninstall()


class TestSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="mode"):
            FaultSpec("x", mode="explode")
        with pytest.raises(ValueError, match="1-based"):
            FaultSpec("x", at=0)
        with pytest.raises(ValueError, match="times"):
            FaultSpec("x", times=0)

    def test_due_window(self):
        s = FaultSpec("x", at=3, times=2)
        assert [s.due(h) for h in range(1, 7)] == [
            False, False, True, True, False, False]
        forever = FaultSpec("x", at=2, times=-1)
        assert not forever.due(1)
        assert all(forever.due(h) for h in range(2, 10))


class TestInjector:
    def test_deterministic_raise_at_nth_hit(self):
        inj = FaultInjector([FaultSpec("site", mode="raise", at=3)])
        assert inj.fire("site") is None
        assert inj.fire("site") is None
        with pytest.raises(FaultError, match="hit 3"):
            inj.fire("site")
        assert inj.fire("site") is None  # times=1: window closed
        assert inj.hits("site") == 4
        assert inj.fired == [("site", "raise", 3)]

    def test_sites_are_independent(self):
        inj = FaultInjector([FaultSpec("a", mode="drop", at=1)])
        assert inj.fire("b") is None
        assert inj.fire("a") == "drop"
        assert inj.hits("a") == 1 and inj.hits("b") == 1

    def test_slow_sleeps_for_delay(self):
        inj = FaultInjector([FaultSpec("s", mode="slow", delay_s=0.05)])
        t0 = time.monotonic()
        assert inj.fire("s") == "slow"
        assert time.monotonic() - t0 >= 0.05

    def test_hang_blocks_until_released(self):
        inj = FaultInjector([FaultSpec("h", mode="hang", delay_s=30.0)])
        done = threading.Event()

        def victim():
            inj.fire("h")
            done.set()

        threading.Thread(target=victim, daemon=True).start()
        assert not done.wait(timeout=0.1)  # parked in the hang
        inj.release()
        assert done.wait(timeout=2.0)  # freed long before delay_s

    def test_thread_safe_hit_counting(self):
        inj = FaultInjector([FaultSpec("c", mode="drop", at=1, times=-1)])
        threads = [threading.Thread(
            target=lambda: [inj.fire("c") for _ in range(100)])
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert inj.hits("c") == 800
        assert len(inj.fired) == 800


class TestModuleGate:
    def test_disarmed_fire_is_noop(self):
        assert faults.active() is None
        assert faults.fire("anything") is None

    def test_inject_context_manager_scopes_arming(self):
        with faults.inject(FaultSpec("x", mode="raise")) as inj:
            assert faults.active() is inj
            with pytest.raises(FaultError):
                faults.fire("x")
        assert faults.active() is None
        assert faults.fire("x") is None

    def test_uninstall_releases_hung_threads(self):
        inj = faults.install(
            FaultInjector([FaultSpec("h", mode="hang", delay_s=30.0)]))
        done = threading.Event()
        threading.Thread(target=lambda: (inj.fire("h"), done.set()),
                         daemon=True).start()
        assert not done.wait(timeout=0.05)
        faults.uninstall()
        assert done.wait(timeout=2.0)

    def test_install_from_env(self, monkeypatch):
        monkeypatch.setenv("KCT_FAULTS", '[{"site": "decode_step", '
                           '"mode": "hang", "at": 5, "delay_s": 1.5}]')
        inj = faults.install_from_env()
        try:
            assert inj is faults.active()
            assert [inj.fire("decode_step") for _ in range(4)] == [None] * 4
        finally:
            faults.uninstall()
        monkeypatch.setenv("KCT_FAULTS", "")
        assert faults.install_from_env() is None

    def test_parse_specs_rejects_non_list(self):
        with pytest.raises(ValueError, match="JSON list"):
            faults.parse_specs('{"site": "x"}')
