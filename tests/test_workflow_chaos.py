"""Orchestrator preemption chaos: SIGKILL the workflow runner mid-step,
rerun, and verify completed steps are skipped and the run finishes.

Extends the ``tests/test_chaos.py`` subprocess pattern one layer up the
stack — there the *trainer* is killed; here the *orchestrator* is, which
is exactly what a GKE node preemption does to an in-cluster runner
(SURVEY §5.3: stricter than the reference's restart hack at
``gpt-neox/04-finetune-workflow.yaml:420-425``).
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(tmp_path):
    """step1 writes its artifact quickly; step2 sleeps a parameterized
    time before writing its own — the kill window."""
    py = sys.executable
    a_out = str(tmp_path / "a.txt")
    b_out = str(tmp_path / "b.txt")
    return {
        "name": "chaos",
        "parameters": {"sleep": "30"},
        "steps": [
            {"name": "fast", "artifacts": [a_out],
             "command": [py, "-c",
                         f"open({a_out!r}, 'w').write('A')"]},
            {"name": "slow", "deps": ["fast"], "artifacts": [b_out],
             "command": [py, "-c",
                         "import time,sys; "
                         "time.sleep(float('{{workflow.parameters.sleep}}'"
                         f")); open({b_out!r}, 'w').write('B')"]},
        ],
    }


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _cli(spec_path, workdir, sleep):
    return [sys.executable, "-m", "kubernetes_cloud_tpu.workflow", "run",
            str(spec_path), "--workdir", str(workdir),
            "-p", f"sleep={sleep}"]


def test_kill_workflow_and_resume(tmp_path):
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(_spec(tmp_path)))
    workdir = tmp_path / "run"

    # phase 1: kill the orchestrator while 'slow' is mid-run
    p = subprocess.Popen(_cli(spec_path, workdir, sleep=30), env=_env(),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            if (tmp_path / "a.txt").exists():
                time.sleep(0.5)  # let 'slow' start
                p.send_signal(signal.SIGKILL)
                break
            if p.poll() is not None:
                raise AssertionError(
                    "runner exited early:\n"
                    + p.stdout.read().decode(errors="replace"))
            time.sleep(0.1)
        else:
            raise AssertionError("fast step never produced its artifact")
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    assert not (tmp_path / "b.txt").exists()
    a_mtime = os.path.getmtime(tmp_path / "a.txt")

    # phase 2: rerun (short sleep) — must resume, not restart
    out = subprocess.run(_cli(spec_path, workdir, sleep=0), env=_env(),
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert (tmp_path / "b.txt").read_text() == "B"
    # completed step was skipped: artifact untouched...
    assert os.path.getmtime(tmp_path / "a.txt") == a_mtime
    # ...and the event log says so explicitly
    from kubernetes_cloud_tpu.workflow.events import read_events

    events = read_events(str(workdir / "events.jsonl"))
    skips = [e for e in events if e["event"] == "step_skipped"
             and e["step"] == "fast"]
    assert skips and skips[-1]["reason"] in ("prior-state",
                                             "sentinel-complete")
    starts = [e for e in events if e["event"] == "step_start"
              and e["step"] == "fast"]
    assert len(starts) == 1  # only the first run ever executed it

    state = json.loads((workdir / "state.json").read_text())
    assert state["steps"]["fast"]["status"] in ("succeeded", "skipped")
    assert state["steps"]["slow"]["status"] == "succeeded"
