import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubernetes_cloud_tpu.core import MeshSpec, build_mesh
from kubernetes_cloud_tpu.weights import (
    Checkpointer,
    latest_checkpoint,
    load_pytree,
    mark_ready,
    read_index,
    wait_ready,
    write_pytree,
)
from kubernetes_cloud_tpu.weights.checkpoint import is_ready


@pytest.fixture
def tree():
    rng = np.random.RandomState(0)
    return {
        "embed": {"wte": rng.randn(32, 16).astype(np.float32)},
        "blocks": {
            "attn": {"wqkv": rng.randn(2, 16, 12, 4).astype(np.float32)},
            "scale": np.ones((2, 16), np.float32),
        },
        "step": np.int32(7),
    }


def test_roundtrip(tmp_path, tree):
    path = str(tmp_path / "model.tensors")
    write_pytree(path, tree, meta={"run": "r1"})
    idx = read_index(path)
    assert idx["meta"]["run"] == "r1"
    assert idx["tensors"]["embed.wte"]["shape"] == [32, 16]
    out = load_pytree(path)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 tree, out)


def test_dtype_cast_on_load(tmp_path, tree):
    path = str(tmp_path / "model.tensors")
    write_pytree(path, {"w": tree["embed"]["wte"]})
    out = load_pytree(path, dtype=jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16


def test_sharded_load(tmp_path, tree, devices8):
    mesh = build_mesh(MeshSpec(data=1, fsdp=4, model=2), devices=devices8)
    path = str(tmp_path / "model.tensors")
    write_pytree(path, tree)
    shardings = {
        "embed": {"wte": NamedSharding(mesh, P("model", "fsdp"))},
        "blocks": {
            "attn": {"wqkv": NamedSharding(mesh,
                                           P(None, "fsdp", "model", None))},
            "scale": None,
        },
        "step": None,
    }
    out = load_pytree(path, shardings)
    assert out["embed"]["wte"].sharding.spec == P("model", "fsdp")
    np.testing.assert_array_equal(np.asarray(out["embed"]["wte"]),
                                  tree["embed"]["wte"])
    np.testing.assert_array_equal(np.asarray(out["blocks"]["attn"]["wqkv"]),
                                  tree["blocks"]["attn"]["wqkv"])


def test_bad_magic(tmp_path):
    path = str(tmp_path / "junk.tensors")
    with open(path, "wb") as f:
        f.write(b"NOTMAGIC" + b"\0" * 64)
    with pytest.raises(ValueError, match="bad magic"):
        read_index(path)


def test_ready_sentinel(tmp_path):
    d = str(tmp_path)
    assert not is_ready(d)
    assert not wait_ready(d, timeout=0.2, poll=0.05)
    mark_ready(d)
    assert wait_ready(d, timeout=0.2, poll=0.05)


def test_latest_checkpoint_discovery(tmp_path):
    assert latest_checkpoint(str(tmp_path / "missing")) is None
    for n in (100, 500, 1000):
        os.makedirs(tmp_path / f"checkpoint-{n}")
    os.makedirs(tmp_path / "not-a-checkpoint")
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint-1000")


def test_checkpointer_save_restore(tmp_path, devices8):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2), devices=devices8)
    state = {
        "params": {"w": jax.device_put(
            jnp.arange(64.0).reshape(8, 8),
            NamedSharding(mesh, P("fsdp", "model")))},
        "step": jnp.int32(3),
    }
    ckpt = Checkpointer(str(tmp_path / "ckpts"), async_save=False)
    assert ckpt.save(500, state)
    ckpt.wait()
    assert ckpt.latest_step() == 500
    restored = ckpt.restore(jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x, state))
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(64.0).reshape(8, 8))
    assert restored["params"]["w"].sharding.spec == P("fsdp", "model")
    ckpt.close()


def test_remote_stream_load(tmp_path, tree, devices8):
    """Remote URIs stream tensors by byte range into (sharded) device
    memory — the GCS cold-start path, exercised via fsspec's in-memory
    filesystem."""
    import fsspec

    from kubernetes_cloud_tpu.weights.tensorstream import is_remote

    local = str(tmp_path / "t.tensors")
    write_pytree(local, tree, meta={"k": 1})
    uri = "memory://bucket/t.tensors"
    assert is_remote(uri) and not is_remote(local)
    # remote write path: write_pytree streams straight to object storage
    # (replaces the reference's S3-upload Job) and must produce the same
    # bytes as the local writer
    write_pytree(uri, tree, meta={"k": 1})
    with open(local, "rb") as srcf, fsspec.open(uri, "rb") as dst:
        assert dst.read() == srcf.read()

    # header over the wire
    idx = read_index(uri)
    assert idx["meta"] == {"k": 1}

    # unsharded remote load == local load (values and integer dtypes)
    remote = load_pytree(uri)
    for a, b in zip(jax.tree.leaves(remote), jax.tree.leaves(tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # sharded remote load places shards on devices, with dtype cast
    mesh = build_mesh(MeshSpec(data=4), devices=devices8[:4])
    shardings = {"embed": {"wte": NamedSharding(mesh, P("data", None))}}
    sharded = load_pytree(uri, shardings, dtype=jnp.bfloat16)
    wte = sharded["embed"]["wte"]
    assert len(wte.addressable_shards) == 4
    assert wte.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(wte, np.float32), tree["embed"]["wte"], rtol=1e-2)
    assert sharded["step"].dtype == jnp.int32  # ints never cast
