"""Streaming weight pipeline failure modes (weights/tensorstream.py).

Every way an artifact can lie must surface as a TYPED error naming
what failed — never params full of garbage, never a bare OSError a
supervisor can't classify: a truncated file, a flipped byte (caught by
the per-chunk crc32, naming tensor AND chunk), a header promising
checksums the blob doesn't have, an mmap whose backing file shrank
mid-load, and transient I/O failures absorbed by the chunk-granular
resume ladder (bounded retries, then ``WeightReadError``).  Plus the
offline gate: ``verify_file`` statuses and the ``kct-tensors-verify``
CLI's distinct exit codes (0 clean / 3 corrupt / 4 truncated /
5 unverifiable).
"""

import json
import os

import numpy as np
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.weights import verify_cli
from kubernetes_cloud_tpu.weights.tensorstream import (
    WeightIntegrityError,
    WeightReadError,
    WeightStreamError,
    WeightTruncatedError,
    load_pytree,
    read_index,
    verify_file,
    weights_version,
    write_pytree,
)

pytestmark = pytest.mark.swap

CHUNK = 256  # tiny chunks so every tensor spans several


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture
def tree():
    rng = np.random.RandomState(0)
    return {"a": rng.randn(40, 10).astype(np.float32),  # 1600 B, 7 chunks
            "b": rng.randn(13).astype(np.float32),
            "c": {"d": np.arange(100, dtype=np.int32)}}


@pytest.fixture
def artifact(tmp_path, tree):
    path = str(tmp_path / "model.tensors")
    write_pytree(path, tree, meta={"run": "r1"}, chunk_bytes=CHUNK)
    return path


def _assert_equal(tree, out):
    import jax

    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 tree, out)


def _strip_checksums(path):
    """Forge a legacy artifact: same blobs, header without crc32 lists
    (padded with whitespace so offsets/data_start stay identical)."""
    with open(path, "r+b") as f:
        assert f.read(8)  # magic
        hlen = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(hlen))
        for info in header["tensors"].values():
            info.pop("crc32", None)
        header.pop("content_hash", None)
        raw = json.dumps(header).encode()
        assert len(raw) <= hlen
        f.seek(16)
        f.write(raw + b" " * (hlen - len(raw)))


# -- header format -----------------------------------------------------------


def test_header_carries_checksums_and_version(artifact, tree):
    idx = read_index(artifact)
    info = idx["tensors"]["a"]
    n_chunks = (tree["a"].nbytes + CHUNK - 1) // CHUNK
    assert len(info["crc32"]) == n_chunks
    assert idx["chunk_bytes"] == CHUNK
    version = weights_version(idx)
    assert version != "unversioned" and len(version) == 12
    # the version is content-derived: same tree, different file → same
    assert weights_version(read_index(artifact)) == version


def test_clean_load_roundtrips_verified(artifact, tree):
    _assert_equal(tree, load_pytree(artifact, verify=True))
    report = verify_file(artifact)
    assert report["status"] == "clean"
    assert report["tensors"] == 3 and not report["errors"]


# -- the four corruption shapes ----------------------------------------------


def test_truncated_file_raises_typed(artifact):
    size = os.path.getsize(artifact)
    with open(artifact, "r+b") as f:
        f.truncate(size - 700)
    with pytest.raises(WeightTruncatedError):
        load_pytree(artifact)
    assert verify_file(artifact)["status"] == "truncated"


def test_flipped_byte_names_tensor_and_chunk(artifact):
    idx = read_index(artifact)
    info = idx["tensors"]["a"]
    # flip one byte inside tensor "a", third chunk
    victim = idx["data_start"] + info["offset"] + 2 * CHUNK + 5
    with open(artifact, "r+b") as f:
        f.seek(victim)
        byte = f.read(1)
        f.seek(victim)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(WeightIntegrityError) as ei:
        load_pytree(artifact)
    assert ei.value.tensor == "a" and ei.value.chunk == 2
    report = verify_file(artifact)
    assert report["status"] == "corrupt"
    assert any("'a'" in e and "chunk 2" in e for e in report["errors"])


def test_header_blob_checksum_mismatch(artifact):
    """A header declaring the wrong number of chunk checksums is a
    header/blob mismatch, not a silent partial verification."""
    with open(artifact, "r+b") as f:
        f.read(8)
        hlen = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(hlen))
        header["tensors"]["a"]["crc32"].pop()  # one checksum short
        raw = json.dumps(header).encode()
        f.seek(16)
        f.write(raw + b" " * (hlen - len(raw)))
    with pytest.raises(WeightIntegrityError, match="header/blob"):
        load_pytree(artifact)
    assert verify_file(artifact)["status"] == "corrupt"


def test_mmap_of_shrunk_file_raises_typed(artifact):
    """The legacy zero-copy path: the backing file shrinking out from
    under the mapping is a typed truncation, not a SIGBUS diagnosis."""
    size = os.path.getsize(artifact)
    with open(artifact, "r+b") as f:
        f.truncate(size - 700)
    with pytest.raises(WeightTruncatedError):
        load_pytree(artifact, streaming=False)


def test_verify_true_demands_checksums(artifact):
    _strip_checksums(artifact)
    with pytest.raises(WeightIntegrityError, match="legacy"):
        load_pytree(artifact, verify=True)
    # default (auto) mode still loads a legacy artifact
    load_pytree(artifact)
    assert verify_file(artifact)["status"] == "unverifiable"


# -- resumable reads under injected I/O faults -------------------------------


def test_resume_survives_transient_chunk_failures(artifact, tree):
    """ISSUE acceptance: chunk-granular restart — three consecutive
    reads fail transiently mid-tensor, the bounded retry ladder absorbs
    all of them (the 4th attempt of the same chunk succeeds), and the
    loaded tree is bit-identical to the clean read."""
    inj = faults.install(faults.FaultInjector([
        FaultSpec(site="weights.read", mode="raise", at=3, times=3)]))
    _assert_equal(tree, load_pytree(artifact))
    assert len(inj.fired) == 3  # the ladder really absorbed all three


def test_exhausted_retries_raise_read_error(artifact):
    faults.install(faults.FaultInjector([
        FaultSpec(site="weights.read", mode="raise",
                  at=1, times=-1)]))  # every read fails
    with pytest.raises(WeightReadError) as ei:
        load_pytree(artifact, retries=2)
    assert ei.value.tensor is not None
    assert isinstance(ei.value, WeightStreamError)


def test_single_dropped_chunk_heals_via_reread(artifact, tree):
    """drop mode zero-fills a chunk in flight: the crc32 refuses it,
    and the single re-read (distinguishing a torn read from corruption
    at rest) gets clean bytes — the load completes verified."""
    inj = faults.install(faults.FaultInjector([
        FaultSpec(site="weights.read", mode="drop", at=3, times=1)]))
    _assert_equal(tree, load_pytree(artifact))
    assert inj.fired == [("weights.read", "drop", 3)]


def test_persistent_dropped_chunk_caught_by_checksum(artifact):
    """A chunk that arrives garbled on the re-read too is corruption,
    and the error names tensor and chunk."""
    faults.install(faults.FaultInjector([
        FaultSpec(site="weights.read", mode="drop", at=3, times=2)]))
    with pytest.raises(WeightIntegrityError) as ei:
        load_pytree(artifact)
    assert ei.value.tensor == "a" and ei.value.chunk is not None


# -- the offline gate (scripts/tensors_verify.py) ----------------------------


def test_cli_exit_codes(artifact, tmp_path):
    assert verify_cli.main([artifact]) == 0
    # corrupt → 3
    idx = read_index(artifact)
    victim = idx["data_start"] + idx["tensors"]["a"]["offset"] + 1
    with open(artifact, "r+b") as f:
        f.seek(victim)
        f.write(b"\xff")
    assert verify_cli.main([artifact]) == 3
    # truncated → 4 (rewrite clean, then truncate)
    write_pytree(artifact, {"a": np.zeros(400, np.float32)},
                 chunk_bytes=CHUNK)
    with open(artifact, "r+b") as f:
        f.truncate(os.path.getsize(artifact) - 500)
    assert verify_cli.main([artifact]) == 4
    # unverifiable (legacy, intact) → 5
    write_pytree(artifact, {"a": np.zeros(400, np.float32)},
                 chunk_bytes=CHUNK)
    _strip_checksums(artifact)
    assert verify_cli.main([artifact]) == 5
    # garbage file → corrupt
    junk = str(tmp_path / "junk.tensors")
    with open(junk, "wb") as f:
        f.write(b"NOTMAGIC" + b"\0" * 64)
    assert verify_cli.main([junk]) == 3


def test_cli_json_report(artifact, capsys):
    assert verify_cli.main(["--format", "json", artifact]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["status"] == "clean"
    assert report["weights_version"] != "unversioned"


def test_cli_worst_verdict_wins(artifact, tmp_path, capsys):
    """Multiple paths: the exit code is the worst verdict across them,
    so a workflow gate can fan one invocation over a whole run dir."""
    clean = str(tmp_path / "clean.tensors")
    write_pytree(clean, {"x": np.ones(64, np.float32)}, chunk_bytes=CHUNK)
    _strip_checksums(artifact)
    assert verify_cli.main([clean, artifact]) == 5
