"""Downloader CLI, image-dataset builder, replicated txt2img service
(reference §2.2 downloader binaries, ``spark/``, ``dalle-mini``)."""

import json
import os
import tarfile

import numpy as np
import pytest

from kubernetes_cloud_tpu.data.downloader_cli import (
    download_dataset,
    download_model,
    is_ready,
    main as downloader_main,
    wait_ready,
)
from kubernetes_cloud_tpu.data.image_dataset_builder import (
    BuilderConfig,
    build,
    read_url_list,
)


def _write_png(path, size=48, color=(200, 30, 40)):
    from PIL import Image

    Image.new("RGB", (size, size), color).save(path)
    return str(path)


class TestDownloader:
    def test_local_model_copy_and_sentinel(self, tmp_path):
        src = tmp_path / "snapshot"
        (src / "sub").mkdir(parents=True)
        (src / "config.json").write_text("{}")
        (src / "sub" / "w.bin").write_bytes(b"\x00" * 8)
        dest = tmp_path / "dest"
        download_model(str(src), str(dest))
        assert (dest / "config.json").exists()
        assert (dest / "sub" / "w.bin").exists()
        assert is_ready(str(dest))
        # idempotent rerun
        download_model(str(src), str(dest))

    def test_dataset_file_urls_and_retry_failure(self, tmp_path):
        corpus = tmp_path / "c.txt"
        corpus.write_text("hello corpus")
        dest = tmp_path / "ds"
        download_dataset([corpus.as_uri()], str(dest))
        assert (dest / "c.txt").read_text() == "hello corpus"
        assert is_ready(str(dest))

        dest2 = tmp_path / "ds2"
        with pytest.raises(RuntimeError):
            download_dataset([(tmp_path / "missing.txt").as_uri()],
                             str(dest2), retries=1)
        assert not is_ready(str(dest2))

    def test_model_download_retries(self, tmp_path, monkeypatch):
        """--retries re-attempts a failed fetch with backoff (reference
        Argo retryStrategy: download=1, the-eye=3) instead of failing on
        the first error."""
        import shutil as shutil_mod

        from kubernetes_cloud_tpu.data import downloader_cli

        src = tmp_path / "snapshot"
        src.mkdir()
        (src / "config.json").write_text("{}")
        attempts = []
        real_copy2 = shutil_mod.copy2

        def flaky_copy2(a, b):
            attempts.append(a)
            if len(attempts) == 1:
                raise OSError("transient I/O error")
            return real_copy2(a, b)

        monkeypatch.setattr(downloader_cli.shutil, "copy2", flaky_copy2)
        monkeypatch.setattr(downloader_cli.time, "sleep", lambda _d: None)
        dest = tmp_path / "dest-retry"
        download_model(str(src), str(dest), retries=1)
        assert len(attempts) == 2
        assert is_ready(str(dest))

        # retries=0 keeps the old fail-fast behavior
        attempts.clear()
        dest2 = tmp_path / "dest-failfast"
        with pytest.raises(RuntimeError, match="failed to fetch"):
            download_model(str(src), str(dest2), retries=0)
        assert len(attempts) == 1
        assert not is_ready(str(dest2))

    def test_wait_ready(self, tmp_path):
        dest = tmp_path / "w"
        dest.mkdir()
        assert not wait_ready(str(dest), timeout=0.2, poll=0.05)
        (dest / ".ready.txt").write_text("1")
        assert wait_ready(str(dest), timeout=0.2, poll=0.05)

    def test_cli_entry(self, tmp_path):
        src = tmp_path / "m"
        src.mkdir()
        (src / "config.json").write_text("{}")
        rc = downloader_main(["model", "--model", str(src),
                              "--dest", str(tmp_path / "out")])
        assert rc == 0
        assert is_ready(str(tmp_path / "out"))


class TestImageDatasetBuilder:
    def _url_list(self, tmp_path, n=5, broken=1):
        paths = [_write_png(tmp_path / f"img{i}.png",
                            color=(i * 40 % 255, 10, 10))
                 for i in range(n)]
        paths += [str(tmp_path / "nope.png")] * broken
        listfile = tmp_path / "urls.tsv"
        listfile.write_text(
            "url\tcaption\n"
            + "".join(f"{p}\tcaption {i}\n" for i, p in enumerate(paths)))
        return str(listfile), n, broken

    def test_read_url_list(self, tmp_path):
        listfile, n, broken = self._url_list(tmp_path)
        rows = read_url_list(listfile)
        assert len(rows) == n + broken
        assert rows[0][1] == "caption 0"

    def test_build_shards_and_stats(self, tmp_path):
        listfile, n, broken = self._url_list(tmp_path)
        out = tmp_path / "wds"
        cfg = BuilderConfig(image_size=32, shard_size=3, workers=4)
        stats = build(listfile, str(out), cfg)
        assert stats["success"] == n
        assert stats["failed"] == broken
        assert stats["shards"] == 2  # 5 ok samples, 3 per shard

        tars = sorted(f for f in os.listdir(out) if f.endswith(".tar"))
        assert len(tars) == 2
        with tarfile.open(out / tars[0]) as tf:
            names = tf.getnames()
            keys = {n.split(".")[0] for n in names}
            for k in keys:
                assert {f"{k}.jpg", f"{k}.txt", f"{k}.json"} <= set(names)
            meta = json.loads(
                tf.extractfile(f"{sorted(keys)[0]}.json").read())
            assert meta["status"] == "success"
            assert meta["width"] == 32
        assert (out / "stats-000.json").exists()

    def test_slicing_partitions_work(self, tmp_path):
        listfile, n, broken = self._url_list(tmp_path, n=6, broken=0)
        s0 = build(listfile, str(tmp_path / "s0"),
                   BuilderConfig(image_size=16, workers=2),
                   slice_index=0, slice_count=2)
        s1 = build(listfile, str(tmp_path / "s1"),
                   BuilderConfig(image_size=16, workers=2),
                   slice_index=1, slice_count=2)
        assert s0["total"] + s1["total"] == 6
        assert s0["success"] + s1["success"] == 6


class TestReplicatedService:
    def test_multi_candidate_generation(self, tmp_path, devices8):
        from tests.test_diffusion import (
            TINY_CLIP,
            TINY_UNET,
            TINY_VAE,
            _write_images,
        )
        from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
        from kubernetes_cloud_tpu.data.diffusion import (
            LocalBase,
            collate_images,
        )
        from kubernetes_cloud_tpu.train.sd_trainer import (
            SDTrainerConfig,
            StableDiffusionTrainer,
        )

        root = _write_images(tmp_path)
        ds = LocalBase(root, size=32, ucg=0.0, seed=0)
        mesh = build_mesh(MeshSpec(data=2), devices=devices8[:2])
        trainer = StableDiffusionTrainer(
            SDTrainerConfig(run_name="rep", output_path=str(tmp_path),
                            batch_size=2, lr=1e-4, epochs=1, save_steps=0,
                            image_log_steps=0, resolution=32, use_ema=False,
                            logs=str(tmp_path / "logs")),
            mesh, ds, collate_images,
            unet_cfg=TINY_UNET, vae_cfg=TINY_VAE, clip_cfg=TINY_CLIP)
        trainer.train()

        from kubernetes_cloud_tpu.serve.replicated import (
            ReplicatedTxt2ImgService,
        )

        svc = ReplicatedTxt2ImgService(
            "dalle", os.path.join(str(tmp_path), "results-rep", "final"),
            devices=devices8[:4])
        svc.load()
        assert svc.n_devices == 4
        out = svc.predict({
            "instances": [{"prompt": "four candidates"}],
            "parameters": {"height": 32, "width": 32,
                           "num_inference_steps": 2, "seed": 3},
        })
        assert len(out["predictions"]) == 4  # one per device by default

        out3 = svc.predict({
            "instances": [{"prompt": "trimmed"}],
            "parameters": {"num_predictions": 3, "height": 32, "width": 32,
                           "num_inference_steps": 2, "seed": 3},
        })
        assert len(out3["predictions"]) == 3
        # candidates differ (independent latents)
        imgs = {p["image_b64"] for p in out3["predictions"]}
        assert len(imgs) == 3
