"""Dynamic batching front-end (reference: Triton's dynamic_batching for
FasterTransformer, ``online-inference/fastertransformer``)."""

import json
import threading
import time
import urllib.request

import pytest

from kubernetes_cloud_tpu.serve.batcher import (
    BatcherConfig,
    BatchingModel,
    load_model_config,
)
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.server import ModelServer


class RecordingModel(Model):
    """Echoes instances; records batch sizes and per-call parameters."""

    def __init__(self, name="inner", delay=0.0):
        super().__init__(name)
        self.batch_sizes = []
        self.call_params = []
        self.delay = delay

    def predict(self, payload):
        insts = payload["instances"]
        self.batch_sizes.append(len(insts))
        self.call_params.append(dict(payload.get("parameters") or {}))
        if self.delay:
            time.sleep(self.delay)
        return {"predictions": [f"out:{x}" for x in insts]}


def make(cfg=None, **inner_kw):
    inner = RecordingModel(**inner_kw)
    m = BatchingModel("lm", inner, cfg or BatcherConfig(
        max_batch_size=4, max_queue_delay_us=20_000))
    m.load()
    return m, inner


def test_single_request_roundtrip():
    m, inner = make()
    try:
        out = m.predict({"instances": ["a", "b"]})
        assert out == {"predictions": ["out:a", "out:b"]}
        assert inner.batch_sizes == [2]
    finally:
        m.stop()


def test_concurrent_requests_coalesce():
    m, inner = make(delay=0.01)
    try:
        results = {}

        def call(i):
            results[i] = m.predict({"instances": [f"r{i}"]})

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            assert results[i]["predictions"] == [f"out:r{i}"]
        # 8 single-instance requests must have been served in fewer than
        # 8 device calls (coalescing happened)
        assert m.stats["batches"] < 8
        assert m.stats["batched_instances"] == 8
        assert max(inner.batch_sizes) > 1
    finally:
        m.stop()


def test_different_params_not_merged():
    m, inner = make(delay=0.01)
    try:
        outs = {}
        temps = {i: 0.1 * (i % 2) for i in range(4)}

        def call(i):
            outs[i] = m.predict({"instances": [f"p{i}"],
                                 "parameters": {"temperature": temps[i]}})

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            assert outs[i]["predictions"] == [f"out:p{i}"]
        # requests with different temperatures must never share one inner
        # call: each executed batch carries exactly one parameter set, and
        # both parameter sets actually executed
        seen = {p["temperature"] for p in inner.call_params}
        assert seen == {0.0, 0.1}
        assert len(inner.call_params) >= 2
    finally:
        m.stop()


def test_stop_then_load_restarts():
    m, inner = make()
    m.stop()
    m.load()
    try:
        assert m.predict({"instances": ["again"]}) == {
            "predictions": ["out:again"]}
    finally:
        m.stop()


def test_oversize_request_rejected():
    m, _ = make()
    try:
        with pytest.raises(ValueError, match="max_batch_size"):
            m.predict({"instances": list("abcde")})
    finally:
        m.stop()


def test_inner_error_propagates_per_request():
    class Exploding(Model):
        def predict(self, payload):
            raise RuntimeError("device on fire")

    m = BatchingModel("boom", Exploding("x"))
    m.load()
    try:
        with pytest.raises(RuntimeError, match="device on fire"):
            m.predict({"instances": ["a"]})
        assert m.ready  # one failed batch must not kill the dispatcher
        with pytest.raises(RuntimeError, match="device on fire"):
            m.predict({"instances": ["b"]})
    finally:
        m.stop()


def test_stop_fails_pending():
    m, _ = make()
    m.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        m.predict({"instances": ["late"]})


def test_stop_timeout_warns_over_live_dispatcher(caplog):
    """stop() returning with the dispatcher still mid-batch used to be
    silent (ready flipped False over a live thread; only a later load()
    noticed) — it must warn."""
    m, _ = make(delay=0.6)
    t = threading.Thread(target=lambda: m.predict({"instances": ["x"]}))
    t.start()
    time.sleep(0.2)  # batch is now executing inside the dispatcher
    with caplog.at_level("WARNING"):
        m.stop(timeout=0.05)
    assert any("did not stop" in r.message for r in caplog.records)
    t.join(timeout=10)
    m.stop()  # dispatcher has drained by now; clean shutdown, no warning


def test_model_config_file(tmp_path):
    cfg_file = tmp_path / "model_config.json"
    cfg_file.write_text(json.dumps({
        "max_batch_size": 16,
        "dynamic_batching": {"max_queue_delay_microseconds": 1234,
                             "max_queue_size": 99},
    }))
    cfg = load_model_config(str(tmp_path))
    assert cfg.max_batch_size == 16
    assert cfg.max_queue_delay_us == 1234
    assert cfg.max_queue_size == 99
    assert load_model_config("/nonexistent") == BatcherConfig()


def test_served_through_http_concurrently():
    m, inner = make(delay=0.01)
    server = ModelServer([m], host="127.0.0.1", port=0)
    server.start()
    try:
        results = []

        def call(i):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/models/lm:predict",
                data=json.dumps({"instances": [f"h{i}"]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                results.append(json.loads(r.read()))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 6
        # HTTP threads fed one dispatcher: batching must have occurred
        assert max(inner.batch_sizes) > 1
    finally:
        server.stop()
        m.stop()
