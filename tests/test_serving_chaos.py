"""Self-healing serving chaos: deterministic faults vs the supervisor.

The proof for serve/supervisor.py + kubernetes_cloud_tpu/faults.py:
a wedged decode loop is detected by heartbeat staleness, the engine is
restarted (fresh slot pool, queued requests transplanted), /readyz
returns to 200, and the recovered engine generates token-identically to
one-shot ``generate``; a crash-looping engine trips the circuit breaker
into permanent unreadiness while /healthz stays 200 throughout.
Everything is CPU-host, inside the quick-lane budget, and deterministic
(the injector fires on exact hit counts, never on timing dice).
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.errors import (
    DeadlineExceededError,
    EngineRestartedError,
    RetryableError,
    StreamTimeoutError,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
)

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def service(params):
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    return svc


def greedy_reference(params, prompt_ids, n):
    out = np.asarray(generate(CFG, params,
                              jnp.asarray([prompt_ids], jnp.int32),
                              max_new_tokens=n, temperature=0.0,
                              pad_token_id=0))
    return out[0, len(prompt_ids):len(prompt_ids) + n].tolist()


def make_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    eng = ContinuousBatchingEngine(CFG, params, EngineConfig(**kw),
                                   eos_token_id=None, pad_token_id=0)
    eng.start()
    return eng


def warm(eng):
    """Compile every program the scenario will hit BEFORE arming faults
    or watchdogs: a first-iteration XLA compile is (correctly)
    indistinguishable from a wedged device, and these tests are about
    injected failures, not cold-start ones."""
    eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait()


def _get_status(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


def _predict(port, prompt, max_new, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps({
            "instances": [prompt],
            "parameters": {"max_new_tokens": max_new, "temperature": 0.0},
        }).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _wait_until(cond, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def test_watchdog_restarts_hung_engine_end_to_end(service):
    """ISSUE acceptance: hang the decode loop mid-stream → the watchdog
    detects it within the heartbeat window, restarts the engine, /readyz
    returns to 200, and the next request is token-identical to one-shot
    generate."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    warm(model.engine)
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.05,
                                             hang_timeout_s=0.3))
    sup.watch(model)
    sup.start()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        assert _get_status(server.port, "/readyz") == 200
        opts = {"MAX_NEW_TOKENS": 6, "TEMPERATURE": 0.0, "TOP_K": 0,
                "TOP_P": 1.0, "SEED": 0, "ECHO_PROMPT": False}
        want = service.generate_texts(["after the storm"], opts)[0]
        _predict(server.port, "after the storm", 6)  # compile warm-up

        # wedge the SECOND decode iteration: the victim request is
        # mid-stream (one token out) when the loop stops turning
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", mode="hang", at=2, delay_s=60.0)]))
        victim: dict = {}

        def doomed():
            try:
                victim["status"] = _predict(server.port, "after the storm",
                                            6)[0]
            except urllib.error.HTTPError as e:
                victim["status"] = e.code

        t = threading.Thread(target=doomed)
        t.start()
        _wait_until(lambda: sup.stats["hangs"] >= 1,
                    what="watchdog hang detection")
        _wait_until(lambda: _get_status(server.port, "/readyz") == 200,
                    what="/readyz back to 200 after restart")
        t.join(timeout=10)
        # the stranded stream failed retryable, not hung
        assert victim["status"] == 503
        assert sup.stats["restarts"] == 1

        faults.uninstall()  # frees the abandoned scheduler thread
        status, out = _predict(server.port, "after the storm", 6)
        assert status == 200
        assert out["predictions"][0]["generated_text"] == want
    finally:
        server.stop()
        sup.stop()
        model.stop()


def test_crashed_engine_unsupervised_readyz_503_healthz_200(service):
    """Honest health split without a supervisor: a dead engine flips
    /readyz to 503 (Knative stops routing) while /healthz stays 200
    (the pod, its weights, and its compile cache survive)."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        assert _get_status(server.port, "/readyz") == 200
        faults.install(faults.FaultInjector([FaultSpec("model_fn")]))
        with pytest.raises(urllib.error.HTTPError) as e:
            _predict(server.port, "crash me", 4)
        assert e.value.code == 503  # retryable, not a hang or a 500
        _wait_until(lambda: not model.engine.alive, what="engine death")
        assert _get_status(server.port, "/readyz") == 503
        assert _get_status(server.port, "/healthz") == 200
        assert isinstance(model.engine.last_error, faults.FaultError)
    finally:
        server.stop()
        model.stop()


def test_circuit_breaker_goes_permanently_unready(service):
    """ISSUE acceptance: repeated injected crashes exhaust the restart
    budget and the circuit opens — the model is permanently unready
    (readyz 503) rather than crash-looping, while /healthz stays 200."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    warm(model.engine)
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.02,
                                             hang_timeout_s=5.0,
                                             max_restarts=1,
                                             restart_window_s=60.0))
    sup.watch(model)
    sup.start()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        # every model program call crashes, forever
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", times=-1)]))

        def crash_once():
            try:
                _predict(server.port, "doomed", 4, timeout=10)
            except urllib.error.HTTPError:
                pass

        deadline = time.monotonic() + 15
        while (sup.stats["circuit_opens"] == 0
               and time.monotonic() < deadline):
            crash_once()
            time.sleep(0.05)
        assert sup.stats["circuit_opens"] == 1
        assert sup.stats["restarts"] == 1  # budget spent before the trip
        assert model.ready is False
        assert _get_status(server.port, "/readyz") == 503
        assert _get_status(server.port, "/healthz") == 200
        # permanently: further checks never resurrect it
        time.sleep(0.1)
        assert _get_status(server.port, "/readyz") == 503
        with pytest.raises(urllib.error.HTTPError) as e:
            _predict(server.port, "still down", 2, timeout=10)
        assert e.value.code == 503
    finally:
        server.stop()
        sup.stop()
        model.stop()


def test_queued_request_transplanted_across_restart(params):
    """Queued (never-admitted) requests survive an engine restart: the
    supervisor re-admits them into the replacement engine and they
    complete token-identically; only the in-flight request fails."""
    class _Shim:
        """Minimal engine-bearing model: exactly the duck-typed surface
        _EngineTarget needs (engine / name / ready / cfg / load)."""

        def __init__(self, engine):
            self.engine = engine
            self.name = "lm"
            self.ready = True
            self.cfg = engine.ecfg

        def load(self):
            self.engine = make_engine(params, slots=1)

    shim = _Shim(make_engine(params, slots=1))
    warm(shim.engine)
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.05,
                                             hang_timeout_s=0.25))
    sup.watch(shim)
    sup.start()
    try:
        prompt_a, prompt_b = list(range(1, 9)), [7, 8, 9]
        want_b = greedy_reference(params, prompt_b, 4)
        # wedge decode hit 3: A is mid-generation, B still queued
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", mode="hang", at=3, delay_s=60.0)]))
        req_a = shim.engine.submit(prompt_a, max_new_tokens=30,
                                   temperature=0.0)
        req_b = shim.engine.submit(prompt_b, max_new_tokens=4,
                                   temperature=0.0)
        with pytest.raises(EngineRestartedError):
            req_a.wait()
        assert req_b.wait() == want_b  # transplanted, then completed
        assert sup.stats["requeued"] == 1
        assert sup.stats["hangs"] == 1
        assert req_b.engine is shim.engine  # follows the replacement
    finally:
        faults.uninstall()
        sup.stop()
        shim.engine.stop()


def test_compile_grace_suppresses_hang_detection(params):
    """A cold-shape prefill compile silences the heartbeat for tens of
    seconds legitimately; the engine's grace window keeps the watchdog
    from reading it as a hang (and from circuit-breaking a cold pod).
    After the grace lifts, the same wedge is detected normally."""

    class _Shim:
        def __init__(self, engine):
            self.engine = engine
            self.name, self.ready = "lm", True
            self.cfg = engine.ecfg

        def load(self):
            self.engine = make_engine(params, slots=1)

    shim = _Shim(make_engine(params, slots=1))
    warm(shim.engine)
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.02,
                                             hang_timeout_s=0.15))
    sup.watch(shim)
    sup.start()
    try:
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", mode="hang", delay_s=60.0)]))
        eng = shim.engine
        # stand in for a cold compile in flight: the wedged decode below
        # is exactly as silent as a real first-shape XLA compile
        eng.grace_until = time.monotonic() + 30.0
        req = eng.submit([1, 2, 3], max_new_tokens=8, temperature=0.0)
        time.sleep(0.6)  # 4x the hang timeout
        assert sup.stats["hangs"] == 0  # grace held
        assert sup.health(shim)["ok"] is True
        eng.grace_until = 0.0  # "compile" over; now it IS a wedge
        _wait_until(lambda: sup.stats["hangs"] == 1,
                    what="hang detection after grace lifted")
        with pytest.raises(EngineRestartedError):
            req.wait()
        # the restart runs on its own thread; wait for the replacement
        _wait_until(lambda: shim.engine is not None and shim.engine.alive,
                    what="replacement engine up")
    finally:
        faults.uninstall()
        sup.stop()
        if shim.engine is not None:
            shim.engine.stop()


def test_abandon_fails_requests_claimed_mid_admission(params):
    """A wedge INSIDE prefill catches requests in the claimed-but-not-
    yet-slotted window: abandon() must fail them too (they are in
    neither the queue nor a slot), or their waiters would hang forever
    against a live-but-wedged engine."""
    eng = make_engine(params, slots=1, max_len=64)
    try:
        warm(eng)
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", mode="hang", delay_s=60.0)]))
        req = eng.submit([1, 2, 3, 4], max_new_tokens=4, temperature=0.0)
        _wait_until(lambda: req.claimed and eng.queue_depth() == 0,
                    what="request claimed by the wedged admission")
        queued = eng.abandon(EngineRestartedError("restart"))
        assert queued == []  # it was not transplantable from the queue
        got = {}

        def waiter():
            try:
                req.wait()
            except Exception as e:  # noqa: BLE001
                got["err"] = e

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        t.join(timeout=5)
        assert isinstance(got.get("err"), EngineRestartedError)
    finally:
        faults.uninstall()
        eng.stop()


def test_deadline_shedding_and_admission_control(params):
    """Deadlines: expired-at-submit → immediate 504-typed error;
    expired-in-queue → shed at admission (no slot burned); queue-age
    admission control refuses work the math proves will miss."""
    eng = make_engine(params, slots=1, max_len=64)
    try:
        warm(eng)
        # slow every iteration so the slot stays busy deterministically
        faults.install(faults.FaultInjector(
            [FaultSpec("iteration", mode="slow", delay_s=0.05, times=-1)]))
        long_req = eng.submit(list(range(1, 9)), max_new_tokens=15,
                              temperature=0.0)
        with pytest.raises(DeadlineExceededError, match="before admission"):
            eng.submit([1, 2], max_new_tokens=2,
                       deadline=time.monotonic() - 0.001)
        # queued behind ~0.75s of slow iterations with a 100ms budget
        doomed = eng.submit([5, 6], max_new_tokens=2, temperature=0.0,
                            deadline=time.monotonic() + 0.1)
        # admission control: with the queue non-empty and a measured
        # iteration time, a tiny budget is refused at the door
        _wait_until(lambda: eng.iter_s is not None,
                    what="iteration EWMA to be measured")
        eng.iter_s = 0.5  # pin the estimate: determinism over realism
        with pytest.raises(DeadlineExceededError, match="deadline miss"):
            eng.submit([3, 4], max_new_tokens=2,
                       deadline=time.monotonic() + 0.01)
        with pytest.raises(DeadlineExceededError, match="expired in queue"):
            doomed.wait()
        assert eng.stats["deadline_shed"] == 1
        assert len(long_req.wait()) == 15  # bystander unaffected
    finally:
        faults.uninstall()
        eng.stop()


def test_deadline_ms_payload_maps_504_over_http(service):
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/models/lm:predict",
            data=json.dumps({"instances": ["x"],
                             "parameters": {"max_new_tokens": 2},
                             "deadline_ms": 0}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 504
    finally:
        server.stop()
        model.stop()


def test_dropped_stream_raises_typed_stream_timeout(params):
    """ISSUE satellite: a stalled stream raises StreamTimeoutError (a
    retryable error carrying engine liveness), never a raw
    queue.Empty."""
    eng = make_engine(params, slots=1, max_len=64)
    try:
        warm(eng)
        faults.install(faults.FaultInjector([
            # every token after the first is lost on the way out …
            FaultSpec("stream", mode="drop", at=2, times=-1),
            # … and iterations are slow enough that the client's window
            # expires long before the generation finishes
            FaultSpec("iteration", mode="slow", delay_s=0.03, times=-1),
        ]))
        req = eng.submit(list(range(1, 9)), max_new_tokens=20,
                         temperature=0.0)
        stream = req.iter_tokens(timeout=0.25)
        first = next(stream)
        with pytest.raises(StreamTimeoutError, match="engine alive"):
            for _ in stream:
                pass
        assert isinstance(first, int)
        # the engine itself is healthy: generation completed internally
        assert len(req.wait()) == 20
    finally:
        faults.uninstall()
        eng.stop()


def test_dead_engine_fails_stream_within_one_poll(params):
    """Engine death mid-stream surfaces in ≤ one 0.5s poll — the
    liveness re-check the satellite asks for — instead of after the
    client's full stream timeout."""
    eng = make_engine(params, slots=1, max_len=64)
    try:
        warm(eng)
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", at=3)]))  # crash on the 3rd program
        req = eng.submit(list(range(1, 9)), max_new_tokens=20,
                         temperature=0.0)
        stream = req.iter_tokens(timeout=30.0)  # generous client window
        next(stream)
        t0 = time.monotonic()
        with pytest.raises((StreamTimeoutError, EngineRestartedError,
                            RetryableError)):
            for _ in stream:
                pass
        assert time.monotonic() - t0 < 5.0  # not the 30s client window
        assert not eng.alive
    finally:
        faults.uninstall()
        eng.stop()
