"""Real multi-process jax.distributed integration: two OS processes
rendezvous through the JobSet env contract and train as one 8-device
global mesh.

The CPU-simulated single-process mesh (conftest) covers sharding math;
this covers what it can't — the actual cross-process runtime path: the
``COORDINATOR_ADDRESS`` bootstrap (``core/distributed.py``), per-host
batch assembly via ``jax.make_array_from_process_local_data``
(``parallel/sharding.shard_batch`` multi-host branch, ``data/tokenized
.sharded_batches``), and collective agreement of loss/step across hosts.
This is the JobSet-launch shape of ``deploy/jobset/*.yaml`` at dev scale.
"""

import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys, json
sys.path.insert(0, {repo!r})
from kubernetes_cloud_tpu.core.distributed import (
    is_primary,
    maybe_initialize_distributed,
)

ran = maybe_initialize_distributed()
assert ran, "expected multi-process init from env"

import jax
import jax.numpy as jnp
import numpy as np

assert jax.process_count() == 2, jax.process_count()

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.data.tokenized import (
    TokenizedDataset,
    sharded_batches,
)
from kubernetes_cloud_tpu.models.causal_lm import PRESETS
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

# 2 processes x 4 local cpu devices = 8 global devices
mesh = build_mesh(MeshSpec(data=4, fsdp=2))
assert mesh.devices.size == 8

# --- shard_batch multi-host branch: global batch = concat of host halves
local = np.full((8, 8), jax.process_index(), np.int32)
g = shard_batch({{"x": local}}, mesh)["x"]
assert g.shape == (16, 8), g.shape  # 2 hosts x 8 local rows
total = float(jnp.sum(g.astype(jnp.float32)))
assert total == 8 * 8 * 1.0, total  # half zeros + half ones

# --- sharded train loop over the mmap dataset
ds = TokenizedDataset({data!r}, context_size=32)
cfg = PRESETS["test-tiny"]
tc = TrainConfig(warmup_steps=2, total_steps=6)
state = init_train_state(cfg, tc, jax.random.key(0), mesh)
step = jax.jit(make_train_step(cfg, tc), donate_argnums=0)
losses = []
for i, batch in enumerate(sharded_batches(ds, 8, mesh, seed=3, epochs=1)):
    state, metrics = step(state, batch)
    losses.append(float(metrics["loss"]))
    if i >= 2:
        break
print(json.dumps({{"rank": jax.process_index(),
                  "primary": is_primary(),
                  "losses": losses,
                  "step": int(state["step"])}}), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training(tmp_path):
    data = str(tmp_path / "data.tokens")
    np.random.RandomState(0).randint(
        2, 500, size=(64, 32)).astype(np.uint16).tofile(data)
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO, data=data))

    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        # Drop any site shims that pin a TPU platform/distributed runtime
        # (e.g. the axon dev shim): these workers must be plain CPU jax.
        inherited = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p and "axon" not in p]
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4"),
            "PYTHONPATH": os.pathsep.join([REPO, *inherited]),
            # the JobSet headless-service contract (core/distributed.py)
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(rank),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"rank failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        # never leak a sibling worker blocked in rendezvous
        for p in procs:
            if p.poll() is None:
                p.kill()

    import json

    recs = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    ranks = sorted(r["rank"] for r in recs)
    assert ranks == [0, 1]
    assert [r["primary"] for r in sorted(recs, key=lambda r: r["rank"])] \
        == [True, False]
    # SPMD: both hosts computed the SAME global losses and step count
    assert recs[0]["losses"] == recs[1]["losses"]
    assert recs[0]["step"] == recs[1]["step"] == 3
    assert all(np.isfinite(r) for r in recs[0]["losses"])
