"""Grouped flash-attention kernel (ops/flash_kernel) vs the XLA path.

Runs the Pallas kernels in interpreter mode on the CPU test machine; the
same code compiles via Mosaic on TPU.  Matmul precision is forced to
``highest`` — the kernel and XLA paths reduce in different orders, so
comparisons are only meaningful with exact fp32 matmuls.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.ops.attention import _mha_xla
from kubernetes_cloud_tpu.ops.flash_kernel import flash_mha, supported
from kubernetes_cloud_tpu.ops.layers import alibi_slopes

pytestmark = pytest.mark.slow  # interpret-mode kernels are minutes on 1 CPU


@pytest.fixture(autouse=True)
def _exact_matmuls():
    with jax.default_matmul_precision("highest"):
        yield


def _ref(q, k, v, *, slopes=None, mask=None, causal=True):
    """XLA reference in kernel layout [B, H, S, D]."""
    d = q.shape[-1]
    bias = None
    if slopes is not None:
        kpos = jnp.arange(k.shape[2], dtype=jnp.float32)
        bias = slopes[None, :, None, None] * kpos[None, None, None, :]
    out = _mha_xla(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                   v.transpose(0, 2, 1, 3), causal=causal, bias=bias,
                   mask=mask, scale=d ** -0.5)
    return out.transpose(0, 2, 1, 3)


def _qkv(b=1, h=4, hkv=2, s=1024, d=32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    return q, k, v


def test_gqa_multiblock_matches_xla():
    """1024-seq = 2 blocks of 512: exercises the online-softmax carry."""
    q, k, v = _qkv()
    got = flash_mha(q, k, v, causal=True, interpret=True)
    want = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_alibi_in_kernel_matches_materialized_bias():
    q, k, v = _qkv(h=4, hkv=4)  # BLOOM is MHA
    slopes = alibi_slopes(4)
    got = flash_mha(q, k, v, slopes=slopes, causal=True, interpret=True)
    want = _ref(q, k, v, slopes=slopes, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_padding_segments_match_xla_mask():
    q, k, v = _qkv(b=2)
    mask = jnp.ones((2, 1024), jnp.int32).at[:, 900:].set(0)
    got = flash_mha(q, k, v, q_seg=mask, kv_seg=mask, causal=True,
                    interpret=True)
    want = _ref(q, k, v, mask=mask, causal=True)
    np.testing.assert_allclose(np.asarray(got)[:, :, :900],
                               np.asarray(want)[:, :, :900],
                               rtol=1e-5, atol=1e-5)


def test_grads_match_xla_gqa_alibi_padded():
    q, k, v = _qkv(b=2, h=4, hkv=2)
    slopes = alibi_slopes(4)
    mask = jnp.ones((2, 1024), jnp.int32).at[:, 1000:].set(0)
    w = mask[:, None, :, None]

    def loss_k(q, k, v):
        return (flash_mha(q, k, v, slopes=slopes, q_seg=mask, kv_seg=mask,
                          causal=True, interpret=True) * w).sum()

    def loss_r(q, k, v):
        return (_ref(q, k, v, slopes=slopes, mask=mask, causal=True)
                * w).sum()

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.abs(b).max()
        assert np.abs(a - b).max() < 1e-4 * scale + 1e-6


def test_wrapper_dispatches_gqa_and_alibi(monkeypatch):
    """attention(impl='auto') routes GQA/ALiBi shapes onto the grouped
    kernel when the pallas backend is available."""
    import importlib

    attn_mod = importlib.import_module("kubernetes_cloud_tpu.ops.attention")
    from kubernetes_cloud_tpu.ops import flash_attention as fa

    monkeypatch.setenv("KCT_FLASH_INTERPRET", "1")
    monkeypatch.setattr(fa, "_MIN_SEQ", 256)

    b, s, h, hkv, d = 1, 256, 4, 2, 32
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    slopes = alibi_slopes(h)

    assert attn_mod._pick_impl(q, k, None, None, slopes) == "pallas"
    got = attn_mod.attention(q, k, v, causal=True, alibi_slopes=slopes)
    want = attn_mod.attention(q, k, v, causal=True, alibi_slopes=slopes,
                              impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_bloom_style_forward_on_kernel_path(monkeypatch):
    """bloom-style preset (ALiBi, MHA) forward: pallas == xla end to end."""
    from kubernetes_cloud_tpu.models.causal_lm import (
        PRESETS,
        forward,
        init_params,
    )

    monkeypatch.setenv("KCT_FLASH_INTERPRET", "1")
    cfg = dataclasses.replace(PRESETS["test-tiny"], pos_emb="alibi",
                              dtype=jnp.float32, attn_impl="pallas")
    params = init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 128), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    got = forward(cfg, params, ids)
    want = forward(dataclasses.replace(cfg, attn_impl="xla"), params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_supported_gates():
    assert supported(2048, 2048, 128, 8, 8)
    assert supported(2048, 2048, 128, 8, 2)
    assert not supported(2048, 2048, 128, 8, 3)       # ragged group
    assert not supported(2000, 2000, 128, 8, 8)       # unaligned seq
    assert not supported(32768, 32768, 128, 8, 8)     # K/V exceed VMEM


def test_fully_masked_rows_zero_output_and_finite_grads():
    """causal=False with disjoint q/kv segments gives query rows with zero
    attention mass.  Forward must output zeros for them and backward must
    stay finite (ADVICE r2: p = exp(s - lse) blew up to ~e^69)."""
    b, h, s, d = 1, 2, 256, 32
    q, k, v = _qkv(b=b, h=h, hkv=h, s=s, d=d, seed=7)
    half = s // 2
    q_seg = jnp.concatenate(
        [jnp.ones((b, half), jnp.int32), jnp.full((b, half), 2, jnp.int32)],
        axis=1)
    kv_seg = jnp.ones((b, s), jnp.int32)  # rows in segment 2 attend nothing

    def loss(q, k, v):
        out = flash_mha(q, k, v, q_seg=q_seg, kv_seg=kv_seg, causal=False,
                        interpret=True)
        return jnp.sum(out ** 2), out

    (val, out), grads = jax.value_and_grad(loss, argnums=(0, 1, 2),
                                           has_aux=True)(q, k, v)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, :, half:, :], 0.0)
    # live rows (segment 1 attends every key) match plain non-causal MHA
    want = np.asarray(_ref(q, k, v, causal=False))
    np.testing.assert_allclose(out[:, :, :half, :], want[:, :, :half, :],
                               rtol=1e-5, atol=1e-5)
    for g in grads:
        g = np.asarray(g)
        assert np.isfinite(g).all()
    # masked query rows contribute no gradient anywhere
    np.testing.assert_array_equal(np.asarray(grads[0])[:, :, half:, :], 0.0)


def test_stock_repeat_route_runs_under_interpret(monkeypatch):
    """Shapes routed 'stock-repeat' (GQA past the grouped VMEM gate) must
    still execute under interpret mode — redirected onto the grouped
    kernel — and match XLA (ADVICE r3 low)."""
    from kubernetes_cloud_tpu.ops import flash_attention as fa

    monkeypatch.setenv("KCT_FLASH_INTERPRET", "1")
    monkeypatch.setattr(fa, "_MIN_SEQ", 512)
    # Force the grouped kernel's VMEM gate shut so _route picks the
    # KV-repeat fallback at a CI-sized shape.
    monkeypatch.setattr(fa.flash_kernel, "supported",
                        lambda *a, **k: False)

    b, s, h, hkv, d = 1, 512, 4, 2, 32
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    assert fa._route(q, k, None, None) == "stock-repeat"
    got = fa.flash_attention(q, k, v, causal=True, bias=None, mask=None,
                             scale=d ** -0.5)
    want = _ref(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=True).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_supports_falls_back_to_stock_kernel_for_huge_gqa():
    """GQA shapes past the grouped kernel's VMEM gate stay on a fused path
    (KV-repeat onto the stock kernel), not impl='xla' (ADVICE r2 medium)."""
    from kubernetes_cloud_tpu.ops import flash_attention as fa

    s, d, hq, hkv = 16384, 128, 8, 2
    q = jax.ShapeDtypeStruct((1, s, hq, d), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((1, s, hkv, d), jnp.bfloat16)
    assert not supported(s, s, d, hq, hkv)      # grouped kernel gated out
    assert fa.supports(q, kv)                   # ...but still fused
    # ALiBi at the same shape has no stock-kernel form -> xla
    assert not fa.supports(q, kv, alibi_slopes=jnp.ones((hq,)))
