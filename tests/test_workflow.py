"""workflow/ orchestrator: spec model, templating, DAG engine, executors,
Argo importer.

All engine tests here drive real subprocesses or fakes — no jax — so the
whole module stays in the quick tier-1 lane.
"""

import json
import os
import sys

import pytest

from kubernetes_cloud_tpu.workflow import (
    RetryStrategy,
    SpecError,
    Step,
    TemplateError,
    WorkflowRun,
    WorkflowSpec,
    artifact_complete,
    evaluate_when,
    render,
)
from kubernetes_cloud_tpu.workflow.argo_import import load_argo_workflow
from kubernetes_cloud_tpu.workflow.events import read_events, summarize
from kubernetes_cloud_tpu.workflow.executors import K8sJobExecutor
from kubernetes_cloud_tpu.workflow.spec import READY_SENTINEL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


# -------------------------------------------------------------------------
# templating


def test_render_parameters():
    params = {"model": "pythia", "pvc": "finetune-data"}
    out = render("/{{workflow.parameters.pvc}}/models/"
                 "{{workflow.parameters.model}}", params)
    assert out == "/finetune-data/models/pythia"


def test_render_unknown_parameter_strict():
    with pytest.raises(TemplateError, match="unknown workflow parameter"):
        render("{{workflow.parameters.nope}}", {})


def test_render_step_outputs():
    out = render("r={{steps.check-model.outputs.result}}", {},
                 {"check-model": "true"})
    assert out == "r=true"
    with pytest.raises(TemplateError, match="no recorded output"):
        render("{{steps.gone.outputs.result}}", {}, {})


def test_render_sprig_replace_and_default():
    params = {"model": "EleutherAI/pythia-2.8b", "tokenizer": "",
              "pvc": "data"}
    # the content-addressed tokenizer output expression from the manifest
    out = render("{{=sprig.replace('/', '_', sprig.replace('.','_', "
                 "sprig.replace('-','_', workflow.parameters.model)))}}",
                 params)
    assert out == "EleutherAI_pythia_2_8b"
    out = render("{{=sprig.default('/' + workflow.parameters.pvc + "
                 "'/models', workflow.parameters.tokenizer)}}", params)
    assert out == "/data/models"
    params["tokenizer"] = "custom"
    out = render("{{=sprig.default('x', workflow.parameters.tokenizer)}}",
                 params)
    assert out == "custom"


def test_render_sprig_ternary():
    params = {"prompt_file": "", "pvc": "data"}
    tmpl = ("{{=workflow.parameters.prompt_file == '' ? '' : '/' + "
            "workflow.parameters.pvc + '/' + "
            "workflow.parameters.prompt_file}}")
    assert render(tmpl, params) == ""
    params["prompt_file"] = "p.txt"
    assert render(tmpl, params) == "/data/p.txt"


def test_sprig_rejects_arbitrary_code():
    with pytest.raises(TemplateError):
        render("{{=__import__('os').system('true')}}", {})
    with pytest.raises(TemplateError):
        render("{{=open('/etc/passwd')}}", {})


def test_evaluate_when():
    params = {"uri": "", "only": "false", "dl": "true"}
    assert evaluate_when("'{{workflow.parameters.uri}}' == ''", params)
    assert not evaluate_when("'{{workflow.parameters.uri}}' != ''", params)
    # the manifest's compound condition
    assert evaluate_when(
        "{{workflow.parameters.only}} == false && "
        "{{workflow.parameters.dl}} == true", params)
    assert not evaluate_when(
        "{{workflow.parameters.only}} == true && "
        "{{workflow.parameters.dl}} == true", params)
    assert evaluate_when("x == y || {{workflow.parameters.dl}} == true",
                         params)
    assert evaluate_when("", params)  # no condition => run


# -------------------------------------------------------------------------
# spec model


def test_spec_validate_topo_and_errors():
    spec = WorkflowSpec("w", steps=[
        Step("c", ["true"], deps=["a", "b"]),
        Step("a", ["true"]),
        Step("b", ["true"], deps=["a"]),
    ])
    order = spec.validate()
    assert order.index("a") < order.index("b") < order.index("c")

    with pytest.raises(SpecError, match="unknown step"):
        WorkflowSpec("w", steps=[Step("a", ["true"], deps=["ghost"])
                                 ]).validate()
    with pytest.raises(SpecError, match="cycle"):
        WorkflowSpec("w", steps=[
            Step("a", ["true"], deps=["b"]),
            Step("b", ["true"], deps=["a"]),
        ]).validate()
    with pytest.raises(SpecError, match="duplicate"):
        WorkflowSpec("w", steps=[Step("a", ["true"]),
                                 Step("a", ["true"])]).validate()


def test_resolve_parameters():
    spec = WorkflowSpec("w", steps=[Step("a", ["true"])],
                        parameters={"req": None, "opt": "x"})
    with pytest.raises(SpecError, match="missing required"):
        spec.resolve_parameters()
    with pytest.raises(SpecError, match="unknown parameter"):
        spec.resolve_parameters({"req": "1", "typo": "2"})
    assert spec.resolve_parameters({"req": "1"}) == {"req": "1", "opt": "x"}


def test_retry_backoff_schedule():
    import random

    r = RetryStrategy(limit=5, backoff=1.0, factor=2.0, max_backoff=5.0,
                      jitter=0.0)
    rng = random.Random(0)
    assert [r.delay(i, rng) for i in range(4)] == [1.0, 2.0, 4.0, 5.0]
    jittered = RetryStrategy(backoff=1.0, jitter=0.5).delay(0, rng)
    assert 1.0 <= jittered <= 1.5


def test_spec_roundtrip():
    spec = WorkflowSpec("w", parameters={"p": "1"}, steps=[
        Step("a", ["echo", "{{workflow.parameters.p}}"],
             retry=RetryStrategy(limit=2), artifacts=["/tmp/x"],
             env={"K": "V"}, when="a == a"),
    ])
    back = WorkflowSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert back == spec


def test_sentinel_matches_checkpoint_contract(tmp_path):
    from kubernetes_cloud_tpu.weights.checkpoint import (
        READY_SENTINEL as CKPT_SENTINEL,
    )

    assert READY_SENTINEL == CKPT_SENTINEL
    d = tmp_path / "artifact"
    d.mkdir()
    assert not artifact_complete(str(d))
    (d / READY_SENTINEL).write_text("ready")
    assert artifact_complete(str(d))
    f = tmp_path / "out.tokens"
    assert not artifact_complete(str(f))
    f.write_bytes(b"\0")
    assert artifact_complete(str(f))


# -------------------------------------------------------------------------
# engine


def _sleeps():
    delays = []

    def fake_sleep(d):
        delays.append(d)

    return delays, fake_sleep


def test_engine_dag_concurrency_and_outputs(tmp_path):
    marker = tmp_path / "order.txt"
    spec = WorkflowSpec("t", parameters={"msg": "hi"}, steps=[
        Step("a", [PY, "-c", "print('A-{{workflow.parameters.msg}}')"]),
        Step("b", [PY, "-c", "print('B')"]),
        Step("join", [PY, "-c",
                      f"open({str(marker)!r},'w').write("
                      "'{{steps.a.outputs.result}}+"
                      "{{steps.b.outputs.result}}')"],
             deps=["a", "b"]),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["status"] == "succeeded"
    assert result["outputs"]["a"] == "A-hi"
    assert marker.read_text() == "A-hi+B"
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    kinds = [e["event"] for e in events]
    assert kinds[0] == "workflow_start" and kinds[-1] == "workflow_finish"
    # join must start only after both finishes
    idx = {(e["event"], e.get("step")): i for i, e in enumerate(events)}
    assert idx[("step_start", "join")] > idx[("step_finish", "a")]
    assert idx[("step_start", "join")] > idx[("step_finish", "b")]


def test_engine_retry_events_and_backoff(tmp_path):
    """A step configured with retryStrategy(limit=3) retries with backoff
    and the JSONL event log records each attempt (acceptance criterion)."""
    flag = tmp_path / "flag"
    code = (f"import os,sys; p={str(flag)!r}\n"
            "if os.path.exists(p): sys.exit(0)\n"
            "open(p,'w').close(); sys.exit(1)")
    spec = WorkflowSpec("t", steps=[
        Step("flaky", [PY, "-c", code],
             retry=RetryStrategy(limit=3, backoff=0.2, factor=2.0,
                                 jitter=0.0)),
    ])
    delays, fake_sleep = _sleeps()
    result = WorkflowRun(spec, str(tmp_path / "run"),
                         sleep=fake_sleep).run()
    assert result["status"] == "succeeded"
    assert delays == [0.2]  # one retry, exponential base
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    starts = [e for e in events if e["event"] == "step_start"]
    retries = [e for e in events if e["event"] == "step_retry"]
    assert len(starts) == 2 and len(retries) == 1
    assert retries[0]["delay"] == pytest.approx(0.2)
    assert summarize(events)["flaky"]["attempts"] == 2


def test_engine_retry_exhaustion_fails(tmp_path):
    spec = WorkflowSpec("t", steps=[
        Step("bad", [PY, "-c", "import sys; sys.exit(3)"],
             retry=RetryStrategy(limit=2, backoff=0.01)),
        Step("child", [PY, "-c", "print('x')"], deps=["bad"]),
    ])
    delays, fake_sleep = _sleeps()
    result = WorkflowRun(spec, str(tmp_path / "run"),
                         sleep=fake_sleep).run()
    assert result["status"] == "failed"
    assert result["steps"]["bad"] == "failed"
    assert len(delays) == 2  # limit=2 => 3 attempts, 2 backoffs
    # fail-fast: the child never started
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    assert not any(e["event"] == "step_start" and e["step"] == "child"
                   for e in events)


def test_engine_upstream_failure_propagates(tmp_path):
    # two roots: one fails, one succeeds; only the failed branch is marked
    spec = WorkflowSpec("t", steps=[
        Step("bad", [PY, "-c", "import sys; sys.exit(1)"]),
        Step("child", [PY, "-c", "print('x')"], deps=["bad"]),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["steps"] == {"bad": "failed", "child": "upstream_failed"}


def test_engine_timeout_kills_step(tmp_path):
    spec = WorkflowSpec("t", steps=[
        Step("slow", [PY, "-c", "import time; time.sleep(60)"],
             timeout=0.5),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["status"] == "failed"
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    finish = [e for e in events if e["event"] == "step_finish"][0]
    assert finish["rc"] == 124


def test_engine_when_skip_satisfies_deps(tmp_path):
    spec = WorkflowSpec("t", parameters={"go": "false"}, steps=[
        Step("gated", [PY, "-c", "print('g')"],
             when="{{workflow.parameters.go}} == true"),
        Step("after", [PY, "-c", "print('a')"], deps=["gated"]),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["steps"] == {"gated": "skipped", "after": "succeeded"}


def test_engine_resume_skips_state_and_sentinel(tmp_path):
    """Preemption-safe resume: prior-state steps and sentinel-complete
    artifacts are both skipped on rerun."""
    out_dir = tmp_path / "artifact"
    spec = WorkflowSpec("t", steps=[
        Step("make", [PY, "-c",
                      f"import os; d={str(out_dir)!r}; os.makedirs(d, "
                      f"exist_ok=True); open(os.path.join(d, "
                      f"{READY_SENTINEL!r}), 'w').close()"],
             artifacts=[str(out_dir)]),
        Step("use", [PY, "-c", "print('used')"], deps=["make"]),
    ])
    run1 = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert run1["status"] == "succeeded"

    # rerun in the same workdir: both steps skip via prior state
    run2 = WorkflowRun(spec, str(tmp_path / "run")).run()
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    skips = [e for e in events if e["event"] == "step_skipped"]
    assert {e["step"] for e in skips} == {"make", "use"}
    assert all(e["reason"] == "prior-state" for e in skips)
    assert run2["status"] == "succeeded"

    # fresh workdir, artifact already on disk: sentinel-complete skip
    run3 = WorkflowRun(spec, str(tmp_path / "run2")).run()
    assert run3["status"] == "succeeded"
    events = read_events(str(tmp_path / "run2" / "events.jsonl"))
    skip = [e for e in events if e["event"] == "step_skipped"][0]
    assert skip["step"] == "make" and skip["reason"] == "sentinel-complete"
    # "use" has no artifacts => really ran
    assert any(e["event"] == "step_start" and e["step"] == "use"
               for e in events)


def test_engine_resume_requires_same_params(tmp_path):
    """Prior state resumes only the *same* run: different -p overrides
    re-execute (their artifacts land elsewhere) instead of reporting
    success for work the new run never did."""
    spec = WorkflowSpec("t", parameters={"tag": "a"}, steps=[
        Step("write", [PY, "-c", "print('tag={{workflow.parameters.tag}}')"]),
    ])
    run1 = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert run1["outputs"]["write"] == "tag=a"
    run2 = WorkflowRun(spec, str(tmp_path / "run"),
                       params={"tag": "b"}).run()
    assert run2["outputs"]["write"] == "tag=b"  # re-executed, not skipped
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    starts = [e for e in events if e["event"] == "step_start"]
    assert len(starts) == 2


def test_engine_no_resume_flag(tmp_path):
    spec = WorkflowSpec("t", steps=[Step("a", [PY, "-c", "print('x')"])])
    WorkflowRun(spec, str(tmp_path / "run")).run()
    run2 = WorkflowRun(spec, str(tmp_path / "run")).run(resume=False)
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    starts = [e for e in events
              if e["event"] == "step_start" and e["step"] == "a"]
    assert len(starts) == 2 and run2["status"] == "succeeded"


# -------------------------------------------------------------------------
# k8s executor (fake client)


class FakeClient:
    def __init__(self, fail_polls=1, outcome="succeeded"):
        self.created = []
        self.patched = []
        self.polls = 0
        self.fail_polls = fail_polls
        self.outcome = outcome

    def create(self, path, manifest):
        self.created.append((path, manifest))
        return manifest

    def patch(self, path, manifest):
        self.patched.append((path, manifest))
        return manifest

    def get(self, path):
        self.polls += 1
        if self.polls <= self.fail_polls:
            return {"status": {"active": 1}}
        return {"status": {self.outcome: 1}}


def test_k8s_job_executor_success():
    client = FakeClient(fail_polls=2)
    ex = K8sJobExecutor(client, namespace="ml", sleep=lambda _d: None)
    step = Step("train-step", ["python3", "-m", "x"],
                image="ghcr.io/img:1", env={"WORKFLOW_RUN_ID": "r1",
                                            "A": "b"})
    result = ex.execute(step, timeout=60)
    assert result.ok
    path, manifest = client.created[0]
    assert path == "/apis/batch/v1/namespaces/ml/jobs"
    assert manifest["spec"]["backoffLimit"] == 0  # engine owns retries
    container = manifest["spec"]["template"]["spec"]["containers"][0]
    assert container["image"] == "ghcr.io/img:1"
    assert container["command"] == ["python3", "-m", "x"]
    assert manifest["metadata"]["name"].startswith("r1-train-step")


def test_k8s_job_executor_failure_and_timeout():
    ex = K8sJobExecutor(FakeClient(fail_polls=0, outcome="failed"),
                        sleep=lambda _d: None)
    assert ex.execute(Step("s", ["x"]), timeout=60).rc == 1

    class NeverDone(FakeClient):
        def get(self, path):
            return {"status": {"active": 1}}

    ex = K8sJobExecutor(NeverDone(), sleep=lambda _d: None, poll=0.0)
    assert ex.execute(Step("s", ["x"]), timeout=-1).rc == 124


def test_k8s_job_retry_names_and_409_tolerance():
    """Each attempt creates a distinctly-named Job; a 409 on create (lost
    response replayed, or a prior orchestrator died post-create) polls the
    existing Job instead of failing."""
    from kubernetes_cloud_tpu.deploy.k8s_client import ApiError

    client = FakeClient(fail_polls=0)
    ex = K8sJobExecutor(client, sleep=lambda _d: None)
    step = Step("s", ["x"], env={"WORKFLOW_RUN_ID": "r1"})
    ex.execute(step, timeout=60, attempt=0)
    ex.execute(step, timeout=60, attempt=1)
    names = [m["metadata"]["name"] for _p, m in client.created]
    assert names == ["r1-s-a0", "r1-s-a1"]
    # the attempt suffix survives the 63-char DNS-label truncation
    long_step = Step("x" * 70, ["x"], env={"WORKFLOW_RUN_ID": "r1"})
    manifest = ex.job_manifest(long_step, "r1", attempt=7)
    name = manifest["metadata"]["name"]
    assert len(name) <= 63 and name.endswith("-a7")

    class Conflict(FakeClient):
        def create(self, path, manifest):
            raise ApiError(409, "exists")

    assert K8sJobExecutor(Conflict(fail_polls=0),
                          sleep=lambda _d: None).execute(
        step, timeout=60).ok


def test_engine_skipped_step_output_renders_empty(tmp_path):
    """A sentinel-skipped step has no captured stdout; downstream
    {{steps.x.outputs.result}} resolves to '' instead of crashing."""
    artifact = tmp_path / "a.txt"
    artifact.write_text("done")
    spec = WorkflowSpec("t", steps=[
        Step("make", [PY, "-c", "print('never runs')"],
             artifacts=[str(artifact)]),
        Step("use", [PY, "-c",
                     "print('got:[{{steps.make.outputs.result}}]')"],
             deps=["make"]),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["status"] == "succeeded"
    assert result["outputs"]["use"] == "got:[]"


def test_engine_bad_template_fails_step_not_engine(tmp_path):
    spec = WorkflowSpec("t", steps=[
        Step("bad", [PY, "-c", "print('{{workflow.parameters.nope}}')"]),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["status"] == "failed"
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    finish = [e for e in events if e["event"] == "step_finish"][0]
    assert "TemplateError" in finish["stderr"]
    assert events[-1]["event"] == "workflow_finish"  # clean shutdown


def test_k8s_resource_apply():
    client = FakeClient()
    ex = K8sJobExecutor(client, namespace="ml")
    manifest = ("apiVersion: serving.kserve.io/v1beta1\n"
                "kind: InferenceService\n"
                "metadata:\n  name: svc-1\n")
    result = ex.execute(Step("apply", [], manifest=manifest))
    assert result.ok and result.output == "svc-1"
    path, body = client.created[0]
    assert path == ("/apis/serving.kserve.io/v1beta1/namespaces/ml/"
                    "inferenceservices")
    assert body["kind"] == "InferenceService"


# -------------------------------------------------------------------------
# argo importer over the shipped manifests


def test_import_finetune_workflow():
    spec = load_argo_workflow(os.path.join(
        REPO, "deploy", "finetuner-workflow", "finetune-workflow.yaml"))
    assert len(spec.parameters) == 56  # reference parity (SURVEY §5.6)
    assert spec.parameters["run_name"] is None  # required
    order = spec.validate()
    assert order == ["check-model", "model-downloader",
                     "dataset-downloader", "tokenizer", "finetuner",
                     "inference-service"]
    # retryStrategy carried over
    assert spec.step("model-downloader").retry.limit == 1
    # sequential groups: each step depends on the previous group
    assert spec.step("tokenizer").deps == ["dataset-downloader"]
    # container command became argv, carried verbatim (executors own any
    # local remapping)
    dl = spec.step("model-downloader")
    assert dl.command[:3] == ["python3", "-m",
                              "kubernetes_cloud_tpu.data.downloader"]
    # inputs substituted: the step's --model arg templates the workflow param
    assert "{{workflow.parameters.model}}" in " ".join(dl.command)
    assert "{{inputs.parameters" not in " ".join(dl.command)
    # resource template kept for the k8s executor
    isvc = spec.step("inference-service")
    assert isvc.executor == "k8s" and "InferenceService" in isvc.manifest
    # when conditions preserved
    assert spec.step("finetuner").when


def test_import_withparam_fanout():
    path = os.path.join(REPO, "deploy", "argo-workflow",
                        "tpu-say-workflow.yaml")
    spec = load_argo_workflow(path)
    names = [s.name for s in spec.steps]
    assert names == [f"tpu-say-{i}" for i in range(4)]
    # {{item}} substituted into each instance
    assert any("Hello" in " ".join(s.command) for s in spec.steps)
    assert all(s.retry.limit == 1 for s in spec.steps)

    # -p overrides reshape the fan-out at import time
    spec2 = load_argo_workflow(path, {"messages": '["x", "y"]'})
    assert [s.name for s in spec2.steps] == ["tpu-say-0", "tpu-say-1"]
    assert any("y" in " ".join(s.command) for s in spec2.steps)


def test_import_missing_required_input_errors(tmp_path):
    """A defaultless template input with no supplied argument is an import
    error, not a literal 'None' in the argv."""
    doc = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata: {generateName: broken-}
spec:
  entrypoint: main
  templates:
    - name: main
      steps:
        - - name: s1
            template: worker
    - name: worker
      inputs:
        parameters:
          - name: dest
      container:
        image: img
        command: [run, "{{inputs.parameters.dest}}"]
"""
    path = tmp_path / "w.yaml"
    path.write_text(doc)
    with pytest.raises(SpecError, match="'dest' not supplied"):
        load_argo_workflow(str(path))


def test_imported_tokenizer_command_remapped_locally_only():
    """The spec keeps the container's verbatim argv (so --executor k8s
    ships the image's own binary); only the local executor remaps it to
    the in-tree CLI."""
    from kubernetes_cloud_tpu.workflow.executors import LocalExecutor

    spec = load_argo_workflow(os.path.join(
        REPO, "deploy", "finetuner-workflow", "finetune-workflow.yaml"))
    tok = spec.step("tokenizer")
    assert tok.command[0] == "/usr/local/bin/dataset_tokenizer"
    argv = LocalExecutor()._argv(tok)
    assert argv[:3] == [
        sys.executable, "-m", "kubernetes_cloud_tpu.data.tokenizer_cli"]
    assert argv[3:] == tok.command[1:]


def test_engine_unregistered_executor_fails_step(tmp_path):
    spec = WorkflowSpec("t", steps=[
        Step("apply", [], executor="k8s", manifest="kind: X"),
    ])
    result = WorkflowRun(spec, str(tmp_path / "run")).run()
    assert result["status"] == "failed"
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    finish = [e for e in events if e["event"] == "step_finish"][0]
    assert "no 'k8s' executor registered" in finish["stderr"]
    assert events[-1]["event"] == "workflow_finish"


# -------------------------------------------------------------------------
# CLI


def test_cli_list_and_import(tmp_path, capsys):
    from kubernetes_cloud_tpu.workflow.cli import main

    assert main(["list"]) == 0
    assert "finetune-and-serve" in capsys.readouterr().out

    out = tmp_path / "spec.json"
    rc = main(["import",
               os.path.join(REPO, "deploy", "finetuner-workflow",
                            "finetune-workflow.yaml"),
               "-o", str(out)])
    assert rc == 0
    spec = WorkflowSpec.from_dict(json.loads(out.read_text()))
    assert len(spec.steps) == 6


def test_cli_run_spec_file_and_status(tmp_path, capsys):
    from kubernetes_cloud_tpu.workflow.cli import main

    spec = WorkflowSpec("mini", parameters={"msg": None}, steps=[
        Step("hello", [PY, "-c", "print('{{workflow.parameters.msg}}')"]),
    ])
    path = tmp_path / "spec.json"
    path.write_text(json.dumps(spec.to_dict()))
    workdir = tmp_path / "run"
    rc = main(["run", str(path), "-p", "msg=yo", "--workdir", str(workdir)])
    assert rc == 0
    assert "hello" in capsys.readouterr().out
    rc = main(["status", "--workdir", str(workdir)])
    assert rc == 0
    assert "succeeded" in capsys.readouterr().out


def test_cli_run_missing_required_param(tmp_path, capsys):
    from kubernetes_cloud_tpu.workflow.cli import main

    spec = WorkflowSpec("mini", parameters={"msg": None}, steps=[
        Step("hello", [PY, "-c", "print('x')"]),
    ])
    path = tmp_path / "spec.json"
    path.write_text(json.dumps(spec.to_dict()))
    rc = main(["run", str(path), "--workdir", str(tmp_path / "r")])
    assert rc == 2
    assert "missing required" in capsys.readouterr().out
