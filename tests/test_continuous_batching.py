"""Continuous-batching engine: correctness lock + serving contract.

The lock: iteration-level scheduling (serve/continuous.py) must produce
greedy outputs token-identical to one-shot ``generate`` for the same
prompts, for ANY admission order — slots are reused across requests, so
a stale cache row, a wrong per-slot length, or cross-row leakage in
``decode_step_slots`` all show up here as token divergence.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.batcher import QueueFullError
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    ContinuousBatchingModel,
    EngineConfig,
    load_engine_config,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def reference(params):
    """One-shot greedy completions, per prompt (batch 1: no co-batching
    effects in the reference either)."""
    refs = []
    for p, n in zip(PROMPTS, MAX_NEW):
        out = np.asarray(generate(CFG, params, jnp.asarray([p], jnp.int32),
                                  max_new_tokens=n, temperature=0.0,
                                  pad_token_id=0))
        refs.append(out[0, len(p):len(p) + n].tolist())
    return refs


def make_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    eng = ContinuousBatchingEngine(CFG, params, EngineConfig(**kw),
                                   eos_token_id=None, pad_token_id=0)
    eng.start()
    return eng


@pytest.mark.parametrize("order", [[0, 1, 2, 3], [3, 2, 1, 0], [2, 0, 3, 1]])
def test_token_identical_to_generate_any_admission_order(params, reference,
                                                         order):
    # slots < requests forces queueing + slot reuse mid-run
    eng = make_engine(params)
    try:
        reqs = {i: eng.submit(PROMPTS[i], max_new_tokens=MAX_NEW[i],
                              temperature=0.0) for i in order}
        for i in order:
            assert reqs[i].wait(eng) == reference[i]
    finally:
        eng.stop()
    assert eng.stats["evictions"] == len(PROMPTS)


def test_streaming_tokens_arrive_incrementally(params, reference):
    eng = make_engine(params)
    try:
        req = eng.submit(PROMPTS[0], max_new_tokens=MAX_NEW[0],
                         temperature=0.0)
        streamed = list(req.iter_tokens(timeout=60))
        assert streamed == reference[0]
        assert req.wait(eng) == reference[0]
        assert req.first_token_at is not None
        assert req.done_at >= req.first_token_at
    finally:
        eng.stop()


def test_eos_evicts_slot_early(params, reference):
    # use the first greedy token as eos: generation must stop after it
    eos = reference[0][0]
    eng = ContinuousBatchingEngine(
        CFG, params, EngineConfig(slots=2, max_len=64),
        eos_token_id=eos, pad_token_id=0)
    eng.start()
    try:
        req = eng.submit(PROMPTS[0], max_new_tokens=6, temperature=0.0)
        assert req.wait(eng) == [eos]
    finally:
        eng.stop()


def test_backpressure_queue_full(params):
    eng = make_engine(params, slots=1, max_queue_size=1)
    try:
        held = eng.submit(PROMPTS[2], max_new_tokens=4, temperature=0.0)
        # saturate: one may be admitted quickly, so pump until the bound
        # trips — the queue bound must surface as QueueFullError, not hang
        with pytest.raises(QueueFullError):
            for _ in range(64):
                eng.submit(PROMPTS[0], max_new_tokens=40, temperature=0.0)
        held.wait(eng)
    finally:
        eng.stop()


def test_prompt_plus_completion_must_fit_pool(params):
    eng = make_engine(params, max_len=16)
    try:
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(list(range(1, 13)), max_new_tokens=8)
    finally:
        eng.stop()


def test_stop_drains_active_and_fails_queued(params):
    eng = make_engine(params, slots=1)
    try:
        active = eng.submit(PROMPTS[2], max_new_tokens=40, temperature=0.0)
        queued = eng.submit(PROMPTS[0], max_new_tokens=4, temperature=0.0)
        # wait until the first request actually occupies the slot
        next(active.iter_tokens(timeout=60))
        eng.stop()
        assert len(active.wait(eng)) == 40  # drained to completion
        with pytest.raises(RuntimeError, match="stopped"):
            queued.wait(eng)
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit(PROMPTS[0], max_new_tokens=2)
    finally:
        eng.stop()


# -- model wrapper / HTTP integration ---------------------------------------


@pytest.fixture(scope="module")
def service(params):
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    return svc


def test_wrapper_matches_generate_texts(service):
    """The ModelServer-facing wrapper must reproduce the one-shot
    service's greedy output exactly (same tokenizer trim rules)."""
    m = ContinuousBatchingModel("lm", service,
                                EngineConfig(slots=2, max_len=96))
    m.load()
    try:
        prompts = ["hello world", "abc", "a much longer prompt here"]
        opts = {"MAX_NEW_TOKENS": 5, "TEMPERATURE": 0.0, "TOP_K": 0,
                "TOP_P": 1.0, "SEED": 0, "ECHO_PROMPT": False}
        want = service.generate_texts(prompts, opts)
        out = m.predict({"instances": prompts,
                         "parameters": {"max_new_tokens": 5,
                                        "temperature": 0.0}})
        assert [p["generated_text"] for p in out["predictions"]] == want
        assert all(p["tokens_out"] == 5 for p in out["predictions"])
    finally:
        m.stop()


def test_wrapper_served_through_http_concurrently(service):
    m = ContinuousBatchingModel("lm", service,
                                EngineConfig(slots=4, max_len=96))
    m.load()
    server = ModelServer([m], host="127.0.0.1", port=0)
    server.start()
    try:
        results = []

        def call(i):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/models/lm:predict",
                data=json.dumps({
                    "instances": [f"prompt-{i}"],
                    "parameters": {"max_new_tokens": 3 + i,
                                   "temperature": 0.0},
                }).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                results.append(json.loads(r.read()))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 6
        for out in results:
            assert out["predictions"][0]["tokens_out"] >= 3
        # iteration-level scheduling: concurrent mixed-length requests
        # shared decode iterations (strictly fewer than serial decode)
        assert m.engine.stats["active_slot_steps"] \
            > m.engine.stats["iterations"]
    finally:
        server.stop()
        m.stop()


def test_load_refuses_stopped_but_draining_engine(service):
    """A timed-out stop() leaves the scheduler draining; load() must
    refuse (ready=True over a stopped engine would 500 every predict)
    until the drain finishes, then restart cleanly."""
    m = ContinuousBatchingModel(
        "lm", service, EngineConfig(slots=1, max_len=64,
                                    drain_timeout_s=0.01))
    m.load()
    req = m.engine.submit(list(range(1, 9)), max_new_tokens=54,
                          temperature=0.0)
    next(req.iter_tokens(timeout=60))  # generation is now in flight
    m.stop()  # 0.01 s drain timeout: almost certainly still draining
    if m.engine.draining:
        with pytest.raises(RuntimeError, match="draining"):
            m.load()
    deadline = time.monotonic() + 30
    while m.engine.alive and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not m.engine.alive
    m.load()  # drained: restart gets a fresh engine
    try:
        assert m.ready
        out = m.predict({"instances": ["ok"],
                         "parameters": {"max_new_tokens": 2,
                                        "temperature": 0.0}})
        assert out["predictions"][0]["tokens_out"] == 2
    finally:
        m.stop()


def test_engine_config_from_model_config(tmp_path):
    (tmp_path / "model_config.json").write_text(json.dumps({
        "max_batch_size": 8,
        "continuous_batching": {"slots": 16, "max_len": 1024,
                                "max_queue_size": 99,
                                "max_admit_per_step": 2},
    }))
    cfg = load_engine_config(str(tmp_path))
    assert cfg == EngineConfig(slots=16, max_len=1024, max_queue_size=99,
                               max_admit_per_step=2)
    assert load_engine_config("/nonexistent") == EngineConfig()
