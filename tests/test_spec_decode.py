"""Speculative decoding: the token-identity oracle + accounting locks.

Greedy acceptance makes speculative output bitwise the non-speculative
decode — so every test here is an oracle test: whatever the draft
proposes (good, bad, adversarial), outputs must equal the engine
without speculation.  A scripted draft that disagrees at known
positions makes the acceptance-ratio arithmetic exactly assertable;
the ``spec.verify`` fault site must inherit decode_step's containment
contract (a crashed verify program fails loudly, never silently
corrupts).
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.errors import RetryableError
from kubernetes_cloud_tpu.serve.spec_decode import (
    ModelDraft,
    NgramDraft,
    ScriptedDraft,
)
from kubernetes_cloud_tpu.serve.tenancy import TenancyConfig, TenantSpec

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)
#: a genuinely smaller draft LM over the same vocab (the
#: pythia-70m-drafts-for-410m shape, scaled to the test preset)
DRAFT_CFG = dataclasses.replace(CFG, num_layers=1)

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def draft_params():
    return init_params(DRAFT_CFG, jax.random.key(1))


@pytest.fixture(scope="module")
def reference(params):
    refs = []
    for p, n in zip(PROMPTS, MAX_NEW):
        out = np.asarray(generate(CFG, params, jnp.asarray([p], jnp.int32),
                                  max_new_tokens=n, temperature=0.0,
                                  pad_token_id=0))
        refs.append(out[0, len(p):len(p) + n].tolist())
    return refs


def ref_tokens(params, prompt, n):
    out = np.asarray(generate(CFG, params, jnp.asarray([prompt], jnp.int32),
                              max_new_tokens=n, temperature=0.0,
                              pad_token_id=0))
    return out[0, len(prompt):len(prompt) + n].tolist()


def make_engine(params, draft=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    kw.setdefault("spec_draft", "ngram")
    eng = ContinuousBatchingEngine(CFG, params, EngineConfig(**kw),
                                   eos_token_id=None, pad_token_id=0,
                                   draft=draft)
    eng.start()
    return eng


def self_draft(params):
    """Draft == target: proposals are the target's own argmax, so
    acceptance is total — the harness that exercises multi-token
    emission + rollback hardest."""
    return ModelDraft(CFG, params, slots=2, max_len=64, pad_token_id=0)


# ---------------------------------------------------------------------------
# the oracle: outputs identical to non-speculative decode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("order", [[0, 1, 2, 3], [3, 2, 1, 0],
                                   [2, 0, 3, 1]])
def test_identity_any_admission_order_model_draft(params, draft_params,
                                                  reference, order):
    eng = make_engine(params, draft=(DRAFT_CFG, draft_params))
    try:
        reqs = {i: eng.submit(PROMPTS[i], max_new_tokens=MAX_NEW[i],
                              temperature=0.0) for i in order}
        for i in order:
            assert reqs[i].wait(eng) == reference[i]
        assert eng.stats["spec_rounds"] > 0
    finally:
        eng.stop()


def test_identity_full_acceptance_path(params, reference):
    """Self-drafting accepts ~every proposal: multi-token emission per
    verify dispatch, and still bitwise the sequential output."""
    eng = make_engine(params, draft=self_draft(params))
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        for r, want in zip(reqs, reference):
            assert r.wait(eng) == want
        st = eng.stats
        assert st["spec_accepted"] > 0
        # fewer verify dispatches than tokens: speculation actually
        # multiplied tokens-per-dispatch
        assert st["spec_rounds"] < st["emitted_tokens"] - len(PROMPTS)
        assert st["spec_accepted"] <= st["spec_drafted"]
    finally:
        eng.stop()


def test_identity_ngram_draft(params, reference):
    eng = make_engine(params)  # spec_draft="ngram" default
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        for r, want in zip(reqs, reference):
            assert r.wait(eng) == want
        assert isinstance(eng.draft, NgramDraft)
        assert eng.stats["spec_rounds"] > 0
    finally:
        eng.stop()


def test_identity_prefix_sharing(params):
    shared = list(range(200, 232))
    p1, p2 = shared + [1, 2, 3], shared + [4, 5, 6, 7]
    eng = make_engine(params, draft=self_draft(params))
    try:
        r1 = eng.submit(p1, max_new_tokens=6, temperature=0.0)
        assert r1.wait(eng) == ref_tokens(params, p1, 6)
        r2 = eng.submit(p2, max_new_tokens=6, temperature=0.0)
        assert r2.wait(eng) == ref_tokens(params, p2, 6)
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["spec_accepted"] > 0
    finally:
        eng.stop()


def test_identity_int8_arena(params):
    """int8 + speculation vs int8 without: same storage semantics on
    both sides, so greedy outputs must agree token-for-token."""
    base = make_engine(params, kv_dtype="int8", spec_draft=None)
    try:
        want = [base.submit(p, max_new_tokens=n, temperature=0.0
                            ).wait(base)
                for p, n in zip(PROMPTS, MAX_NEW)]
    finally:
        base.stop()
    eng = make_engine(params, kv_dtype="int8",
                      draft=self_draft(params))
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        for r, w in zip(reqs, want):
            assert r.wait(eng) == w
        assert eng.stats["spec_accepted"] > 0
    finally:
        eng.stop()


def test_identity_preempt_resume(params):
    """Speculating slots survive QoS preemption/resume: pinned-page
    resume re-enters the draft lazily and outputs stay identical."""
    ten = TenancyConfig(
        tenants=(TenantSpec("batchy", lane="batch",
                            api_keys=("k-batchy",)),
                 TenantSpec("inter", lane="interactive",
                            api_keys=("k-inter",))),
        min_batch_progress=2)
    eng = make_engine(params, tenancy=ten, draft=self_draft(params))
    b_prompts = [list(range(1, 9)), list(range(40, 45))]
    i_prompt = [7, 8, 9]
    try:
        victims = [eng.submit(p, max_new_tokens=40, temperature=0.0,
                              api_key="k-batchy") for p in b_prompts]
        for v in victims:
            next(v.iter_tokens(timeout=60))
        pre = eng.submit(i_prompt, max_new_tokens=7, temperature=0.0,
                         api_key="k-inter")
        assert pre.wait(eng) == ref_tokens(params, i_prompt, 7)
        for p, v in zip(b_prompts, victims):
            assert v.wait(eng) == ref_tokens(params, p, 40)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["spec_accepted"] > 0
    finally:
        eng.stop()


def test_stochastic_slots_speculate_reproducibly(params):
    """temperature > 0 slots now speculate too, via rejection sampling
    against the verification rows' filtered distributions.  The RNG
    consumption pattern differs from the non-speculative path, so the
    lock is NOT bitwise equality with a drafts-free engine —
    distribution-exactness is locked statistically in
    tests/test_ragged_dispatch.py.  What must hold here: speculation
    actually engages for the stochastic slot, and a fixed seed is
    still fully reproducible run-to-run."""
    prompt = list(range(1, 9))
    outs = []
    for _ in range(2):
        eng = make_engine(params, draft=self_draft(params))
        try:
            # a greedy neighbour exercises the mixed greedy/stochastic
            # emit split inside one verification round
            greedy = eng.submit(PROMPTS[2], max_new_tokens=12,
                                temperature=0.0)
            outs.append(eng.submit(prompt, max_new_tokens=10,
                                   temperature=0.8, seed=7).wait(eng))
            greedy.wait(eng)
            assert eng.stats["spec_rounds"] > 0
            assert eng.stats["spec_drafted"] > 0
        finally:
            eng.stop()
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# acceptance-ratio arithmetic: a scripted draft disagreeing at known
# positions
# ---------------------------------------------------------------------------


def test_scripted_draft_exact_acceptance_accounting(params):
    """Draft proposes the TRUE next tokens but corrupts its second
    proposal: every round accepts exactly one draft and emits exactly
    two tokens, making rounds/drafted/accepted closed-form."""
    prompt = PROMPTS[0]
    n = 9
    truth = ref_tokens(params, prompt, n)

    def script(slot, seq, k):
        done = len(seq) - len(prompt)  # tokens emitted so far
        nxt = truth[done:done + k]
        nxt = nxt + [0] * (k - len(nxt))
        out = list(nxt)
        if len(out) > 1:
            out[1] = (out[1] + 1) % CFG.vocab_size  # known disagreement
        return out

    eng = make_engine(params, draft=ScriptedDraft(script), spec_k=4)
    try:
        req = eng.submit(prompt, max_new_tokens=n, temperature=0.0)
        assert req.wait(eng) == truth
        st = eng.stats
        # token 1 comes from prefill; each round then emits 2 (one
        # accepted draft + the disagreeing bonus) -> 4 rounds
        assert st["spec_rounds"] == 4
        assert st["spec_drafted"] == 16
        assert st["spec_accepted"] == 4
        samples = obs.parse_text(obs.REGISTRY.render())
        assert obs.sample_value(samples, "kct_engine_spec_tokens_total",
                                {"model": "engine",
                                 "result": "accepted"}) >= 4
        assert 0.0 < obs.sample_value(
            samples, "kct_engine_spec_accept_ratio",
            {"model": "engine"}) <= 1.0
    finally:
        eng.stop()


def test_empty_proposals_fall_back_to_plain_decode(params, reference):
    """A round where the draft proposes NOTHING takes the plain
    one-token decode dispatch instead of paying the (k+1)-wide verify
    program for a guaranteed single token — with a never-proposing
    draft the engine must behave (and count) exactly like spec-off,
    while outputs stay identical."""
    eng = make_engine(params,
                      draft=ScriptedDraft(lambda slot, seq, k: []),
                      spec_k=4)
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        for req, want in zip(reqs, reference):
            assert req.wait(eng) == want
        st = eng.stats
        assert st["spec_rounds"] == 0
        assert st["spec_drafted"] == 0
        assert st["spec_accepted"] == 0
    finally:
        eng.stop()


def test_shared_stateful_draft_rejected_across_decode_slices(
        params, draft_params):
    """A ModelDraft is single-owner (its slot pool is engine-local,
    mutated lock-free on the scheduler thread): handing ONE instance
    to several disaggregated decode slices must be refused up front
    instead of racing the pool at runtime.  Stateless sources (ngram)
    stay shareable, and the (cfg, params) form builds a private draft
    per slice."""
    from kubernetes_cloud_tpu.serve.disagg import (
        build_disaggregated_engine,
    )

    cfg2 = EngineConfig(slots=2, max_len=64, paged=True, page_size=8,
                        decode_slices=2)
    shared = ModelDraft(DRAFT_CFG, draft_params, slots=2, max_len=64)
    with pytest.raises(ValueError, match="cannot be shared"):
        build_disaggregated_engine(CFG, params, cfg2, draft=shared)
    # ngram is stateless: sharing is legal
    pair = build_disaggregated_engine(CFG, params, cfg2,
                                      draft=NgramDraft())
    assert all(e.draft is not None for e in pair.decodes)
    # (cfg, params) builds one private ModelDraft per slice
    pair2 = build_disaggregated_engine(
        CFG, params, cfg2, draft=(DRAFT_CFG, draft_params))
    drafts = [e.draft for e in pair2.decodes]
    assert all(isinstance(d, ModelDraft) for d in drafts)
    assert drafts[0] is not drafts[1]


def test_adversarial_draft_never_corrupts(params, reference):
    """A draft proposing garbage every time costs speed only."""
    eng = make_engine(params,
                      draft=ScriptedDraft(
                          lambda slot, seq, k:
                          [(seq[-1] * 7 + j) % CFG.vocab_size
                           for j in range(k)]))
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        for r, want in zip(reqs, reference):
            assert r.wait(eng) == want
        assert eng.stats["spec_drafted"] > 0
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# draft-source units
# ---------------------------------------------------------------------------


def test_ngram_draft_unit():
    d = NgramDraft(max_ngram=3)
    # trailing (8, 9) occurred earlier, followed by 10, 11, 12
    seq = [1, 8, 9, 10, 11, 12, 5, 8, 9]
    assert d.propose({0: seq}, 3) == {0: [10, 11, 12]}
    # no earlier occurrence of any trailing n-gram -> no proposal
    assert d.propose({1: [1, 2, 3, 4]}, 3) == {}


def test_ngram_draft_matches_naive_reference():
    """The bytes.rfind fast path (int32 cells, alignment-checked) is
    exactly the naive rightmost-earlier-occurrence scan — fuzzed over
    token values spanning multiple bytes so cell-boundary byte
    coincidences are exercised."""
    import random

    rng = random.Random(7)

    def naive(seq, max_ngram, k):
        drafts = []
        for n in range(min(max_ngram, len(seq) - 1), 0, -1):
            pat = seq[-n:]
            for i in range(len(seq) - n - 1, -1, -1):
                if seq[i:i + n] == pat:
                    drafts = seq[i + n:i + n + k]
                    break
            if drafts:
                break
        return drafts

    d = NgramDraft(max_ngram=3, window=64)
    for _ in range(300):
        # small alphabet forces repeats; values > 255 span bytes
        vocab = rng.choice([4, 7, 300, 70000])
        seq = [rng.randrange(vocab)
               for _ in range(rng.randrange(1, 40))]
        k = rng.randrange(1, 6)
        got = d.propose({0: seq}, k).get(0, [])
        assert got == naive(seq, 3, k), (seq, k, got)


def test_model_draft_catchup_after_full_accept(params):
    """A fully-accepted round leaves the draft one token behind; the
    next propose() pays exactly the catch-up steps (the bookkeeping
    the draft's host lengths make observable)."""
    eng = make_engine(params, draft=self_draft(params))
    try:
        req = eng.submit(PROMPTS[2], max_new_tokens=16, temperature=0.0)
        assert req.wait(eng) == ref_tokens(params, PROMPTS[2], 16)
        assert eng.draft.stats["catchup_steps"] > 0
        assert eng.draft.stats["prefills"] >= 1
    finally:
        eng.stop()


def test_spec_requires_paged():
    with pytest.raises(ValueError, match="paged"):
        EngineConfig(spec_draft="ngram", paged=False)


def test_model_level_ngram_wiring(params):
    """ContinuousBatchingModel resolves spec_draft='ngram' without a
    draft checkpoint, and the rollout metadata names the draft kind so
    fleet probes can tell a speculating replica mid-restart."""
    from kubernetes_cloud_tpu.serve.continuous import (
        ContinuousBatchingModel,
    )
    from kubernetes_cloud_tpu.serve.lm_service import CausalLMService

    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    m = ContinuousBatchingModel("lm", svc, EngineConfig(
        slots=2, max_len=64, paged=True, page_size=8,
        spec_draft="ngram"))
    m.load()
    try:
        assert isinstance(m.engine.draft, NgramDraft)
        meta = m.serving_metadata()
        assert meta["spec_draft"] == "ngram"
        assert meta["prefill_chunk_tokens"] == 0
    finally:
        m.stop()


# ---------------------------------------------------------------------------
# spec.verify chaos containment
# ---------------------------------------------------------------------------


def test_spec_verify_raise_is_a_loud_crash(params):
    """The decode_step contract: a raising verify program crashes the
    scheduler loudly — in-flight requests fail retryable (503), the
    engine reads dead, nothing silently corrupts."""
    eng = make_engine(params, draft=self_draft(params))
    try:
        warm = eng.submit(PROMPTS[0], max_new_tokens=4, temperature=0.0)
        assert warm.wait(eng) == ref_tokens(params, PROMPTS[0], 4)
        faults.install(faults.FaultInjector(
            [FaultSpec("spec.verify", mode="raise")]))
        doomed = eng.submit(PROMPTS[1], max_new_tokens=8,
                            temperature=0.0)
        with pytest.raises(RetryableError):
            doomed.wait(eng)
        deadline = time.monotonic() + 10
        while eng.alive and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not eng.alive
        assert eng.last_error is not None
    finally:
        faults.uninstall()
        eng.stop()
