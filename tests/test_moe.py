"""MoE FFN + expert-parallel training tests."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models.causal_lm import PRESETS, loss_fn
from kubernetes_cloud_tpu.ops.moe import moe_ffn
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)


def _moe_params(key, d=16, f=32, e=4):
    k1, k2, k3 = jax.random.split(key, 3)
    return (jax.random.normal(k1, (d, e), jnp.float32) * 0.5,
            jax.random.normal(k2, (e, d, f), jnp.float32) * 0.1,
            jax.random.normal(k3, (e, f, d), jnp.float32) * 0.1)


def test_moe_matches_per_token_reference():
    """With ample capacity, MoE output == per-token dense expert compute."""
    router_w, wi, wo = _moe_params(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, 16), jnp.float32)
    y, aux = moe_ffn(x, router_w, wi, wo, top_k=2, capacity_factor=4.0,
                     dtype=jnp.float32)

    xt = np.asarray(x).reshape(-1, 16)
    probs = jax.nn.softmax(xt @ np.asarray(router_w), axis=-1)
    want = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        top = np.argsort(-np.asarray(probs[t]))[:2]
        gates = np.asarray(probs[t])[top]
        gates = gates / gates.sum()
        for g, ei in zip(gates, top):
            h = xt[t] @ np.asarray(wi)[ei]
            h = np.asarray(jax.nn.gelu(jnp.asarray(h), approximate=True))
            want[t] += g * (h @ np.asarray(wo)[ei])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), want,
                               rtol=1e-4, atol=1e-4)
    assert 0.5 < float(aux) < 4.0  # ~1 under balance


def test_moe_capacity_dropping():
    router_w, wi, wo = _moe_params(jax.random.key(2))
    x = jax.random.normal(jax.random.key(3), (2, 16, 16), jnp.float32)
    y_ample, _ = moe_ffn(x, router_w, wi, wo, capacity_factor=4.0,
                         dtype=jnp.float32)
    y_tight, _ = moe_ffn(x, router_w, wi, wo, capacity_factor=0.25,
                         dtype=jnp.float32)
    assert np.all(np.isfinite(np.asarray(y_tight)))
    assert not np.allclose(np.asarray(y_ample), np.asarray(y_tight))


def test_moe_lm_expert_parallel_train(devices8):
    """MoE causal LM: expert-sharded mesh matches the single-device loss."""
    cfg = dataclasses.replace(PRESETS["test-tiny"], moe_experts=4)
    tc = TrainConfig(warmup_steps=2, total_steps=10)
    batch = {"input_ids": jax.random.randint(
        jax.random.key(5), (4, 32), 0, cfg.vocab_size, dtype=jnp.int32)}

    mesh1 = build_mesh(MeshSpec(data=1), devices=devices8[:1])
    state1 = init_train_state(cfg, tc, jax.random.key(0), mesh1)
    loss1, m1 = loss_fn(cfg, state1["params"], batch)
    assert "aux_loss" in m1

    mesh = build_mesh(MeshSpec(data=2, expert=2, fsdp=2), devices=devices8)
    state = init_train_state(cfg, tc, jax.random.key(0), mesh)
    step = jax.jit(make_train_step(cfg, tc), donate_argnums=0)
    state, metrics = step(state, shard_batch(batch, mesh))
    np.testing.assert_allclose(float(metrics["loss"]), float(loss1),
                               rtol=2e-4)
    assert int(state["step"]) == 1


def test_moe_padding_does_not_perturb_real_tokens():
    """Real-token outputs are identical whether or not padding shares the
    batch (pads neither route nor claim capacity)."""
    router_w, wi, wo = _moe_params(jax.random.key(4))
    x = jax.random.normal(jax.random.key(5), (1, 8, 16), jnp.float32)
    pad = jnp.zeros((1, 8, 16), jnp.float32)
    x_padded = jnp.concatenate([x, pad], axis=1)
    mask = jnp.concatenate(
        [jnp.ones((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32)], axis=1)

    # Ample capacity isolates the claim under test: pads must not claim
    # slots or route.  (Capacity itself is computed from the static token
    # count incl. pads, so drop patterns legitimately differ when tight.)
    y_alone, _ = moe_ffn(x, router_w, wi, wo, capacity_factor=4.0,
                         token_mask=jnp.ones((1, 8), jnp.int32),
                         dtype=jnp.float32)
    y_padded, _ = moe_ffn(x_padded, router_w, wi, wo, capacity_factor=4.0,
                          token_mask=mask, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(y_padded[:, :8]),
                               np.asarray(y_alone), rtol=1e-5, atol=1e-6)


def test_moe_no_drop_is_cobatch_independent():
    """With no_drop, a sequence's outputs don't depend on co-batched rows."""
    router_w, wi, wo = _moe_params(jax.random.key(6))
    a = jax.random.normal(jax.random.key(7), (1, 8, 16), jnp.float32)
    other = jax.random.normal(jax.random.key(8), (3, 8, 16), jnp.float32)
    y_alone, _ = moe_ffn(a, router_w, wi, wo, no_drop=True,
                         dtype=jnp.float32)
    y_batch, _ = moe_ffn(jnp.concatenate([a, other]), router_w, wi, wo,
                         no_drop=True, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(y_batch[:1]), np.asarray(y_alone),
                               rtol=1e-5, atol=1e-6)


def test_moe_grouped_dispatch_matches_single_group():
    router_w, wi, wo = _moe_params(jax.random.key(9))
    x = jax.random.normal(jax.random.key(10), (2, 16, 16), jnp.float32)
    y_one, _ = moe_ffn(x, router_w, wi, wo, no_drop=True, group_size=32,
                       dtype=jnp.float32)
    y_grouped, _ = moe_ffn(x, router_w, wi, wo, no_drop=True, group_size=8,
                           dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(y_grouped), np.asarray(y_one),
                               rtol=1e-5, atol=1e-6)


def test_moe_config_validation():
    import pytest
    with pytest.raises(ValueError, match="moe_top_k"):
        dataclasses.replace(PRESETS["test-tiny"], moe_experts=1)


def test_moe_grad_flows_to_router():
    cfg = dataclasses.replace(PRESETS["test-tiny"], moe_experts=4)
    from kubernetes_cloud_tpu.models.causal_lm import init_params
    params = jax.jit(init_params, static_argnums=0)(cfg, jax.random.key(0))
    batch = {"input_ids": jnp.ones((2, 16), jnp.int32)}
    grads = jax.grad(lambda p: loss_fn(cfg, p, batch)[0])(params)
    g_router = np.asarray(grads["blocks"]["moe"]["router"])
    assert np.abs(g_router).max() > 0
