"""Serving tests: real HTTP through a socket, mirroring the reference's
smoke-test scripts (``image-classifier/service/predict_url.sh``,
``tensorizer-isvc/README.md`` curl examples)."""

import dataclasses
import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.serve import ByteTokenizer, CausalLMService, ModelServer

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(scope="module")
def server():
    svc = CausalLMService(
        "lm", CFG, params=init_params(CFG, jax.random.key(0)),
        dtype=jnp.float32)
    srv = ModelServer([svc], host="127.0.0.1", port=0)
    srv.load_all()
    srv.start()
    yield srv
    srv.stop()


def _req(server, path, payload=None):
    url = f"http://127.0.0.1:{server.port}{path}"
    if payload is None:
        r = urllib.request.urlopen(url, timeout=30)
    else:
        r = urllib.request.urlopen(
            urllib.request.Request(
                url, json.dumps(payload).encode(),
                {"Content-Type": "application/json"}),
            timeout=120)
    return json.loads(r.read())


def test_liveness_and_model_list(server):
    assert _req(server, "/")["status"] == "alive"
    assert _req(server, "/v1/models") == {"models": ["lm"]}
    detail = _req(server, "/v1/models/lm")
    assert detail["name"] == "lm" and detail["ready"] is True
    assert detail["state"] == "active"


def test_predict_v1(server):
    out = _req(server, "/v1/models/lm:predict", {
        "instances": ["hello world"],
        "parameters": {"max_new_tokens": 4, "temperature": 0.0},
    })
    assert len(out["predictions"]) == 1
    assert "generated_text" in out["predictions"][0]


def test_predict_batch_and_param_override(server):
    out = _req(server, "/v1/models/lm:predict", {
        "instances": [{"text": "a"}, {"text": "bb"}],
        "parameters": {"MAX_NEW_TOKENS": 2, "TEMPERATURE": 0.0,
                       "ECHO_PROMPT": True},
    })
    preds = out["predictions"]
    assert len(preds) == 2
    assert preds[0]["generated_text"].startswith("a")
    assert preds[1]["generated_text"].startswith("bb")


def test_completion_route(server):
    out = _req(server, "/completion",
               {"prompt": "hi", "max_new_tokens": 3, "temperature": 0.0})
    assert "completion" in out


def test_errors(server):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(server, "/v1/models/nope:predict", {"instances": ["x"]})
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(server, "/v1/models/lm:predict", {"wrong": True})
    assert e.value.code == 400


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    assert tok.decode(tok.encode("héllo ✓")) == "héllo ✓"
