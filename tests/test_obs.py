"""Telemetry layer: registry/exposition-format units, request tracing,
HTTP /metrics on the stdlib front-end, the enriched /readyz body, the
workflow engine's metric families, and the chaos proof that a
wedged/raising metrics scrape can never take down the data plane or
flip /readyz.  Everything here is jax-free and quick-lane."""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.obs import tracing
from kubernetes_cloud_tpu.obs.metrics import Registry
from kubernetes_cloud_tpu.serve import load_test
from kubernetes_cloud_tpu.serve.batcher import BatcherConfig, BatchingModel
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
)
from kubernetes_cloud_tpu.train.metrics import read_jsonl


@pytest.fixture(autouse=True)
def _clean():
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()
    yield
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()


# ---------------------------------------------------------------------------
# registry + exposition format
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_roundtrip():
    reg = Registry()
    c = reg.counter("t_requests_total", "Requests.", ("route", "status"))
    c.labels(route="predict", status="200").inc()
    c.labels(route="predict", status="200").inc(2)
    c.labels(route="predict", status="503").inc()
    g = reg.gauge("t_depth", "Depth.")
    g.set(7)
    g.inc(2)
    g.dec()
    h = reg.histogram("t_lat_seconds", "Latency.", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    samples = obs.parse_text(reg.render())
    assert obs.sample_value(samples, "t_requests_total",
                            {"route": "predict", "status": "200"}) == 3
    assert obs.sample_value(samples, "t_requests_total",
                            {"route": "predict"}) == 4  # summed
    assert obs.sample_value(samples, "t_depth") == 8
    assert obs.sample_value(samples, "t_lat_seconds_count") == 3
    assert obs.sample_value(samples, "t_lat_seconds_sum") == pytest.approx(
        5.55)
    # cumulative buckets: le=0.1 → 1, le=1.0 → 2, +Inf → 3
    assert obs.sample_value(samples, "t_lat_seconds_bucket",
                            {"le": "0.1"}) == 1
    assert obs.sample_value(samples, "t_lat_seconds_bucket",
                            {"le": "1"}) == 2
    assert obs.sample_value(samples, "t_lat_seconds_bucket",
                            {"le": "+Inf"}) == 3


def test_registration_is_get_or_create_and_type_checked():
    reg = Registry()
    a = reg.counter("t_total", "x", ("m",))
    assert reg.counter("t_total", "x", ("m",)) is a
    with pytest.raises(ValueError):
        reg.gauge("t_total", "x", ("m",))  # type clash
    with pytest.raises(ValueError):
        reg.counter("t_total", "x", ("other",))  # label-schema clash
    with pytest.raises(ValueError):
        a.labels(wrong="x")
    with pytest.raises(ValueError):
        a.inc()  # labeled family has no default child
    with pytest.raises(ValueError):
        reg.counter("bad name", "x")


def test_label_values_escape_and_histogram_consistency():
    reg = Registry()
    c = reg.counter("t_weird_total", "Weird.", ("p",))
    c.labels(p='a"b\\c\nd').inc()
    text = reg.render()
    samples = obs.parse_text(text)  # the strict parser must accept it
    (name, labels, value), = samples
    assert labels["p"] == 'a"b\\c\nd' and value == 1

    h = reg.histogram("t_h_seconds", "H.", ("m",), buckets=(1, 2))
    h.labels(m="x").observe(1.5)
    samples = obs.parse_text(reg.render())
    # _count always equals the +Inf bucket (scrape-consistency invariant)
    assert obs.sample_value(samples, "t_h_seconds_count", {"m": "x"}) \
        == obs.sample_value(samples, "t_h_seconds_bucket",
                            {"m": "x", "le": "+Inf"})


def test_parser_rejects_malformed_exposition():
    for bad in ("no_value_here\n", "1leading_digit 3\n",
                'm{unterminated="x 1\n', "# BOGUS comment\n",
                "m notanumber\n"):
        with pytest.raises(ValueError):
            obs.parse_text(bad)


def test_registry_reset_zeroes_but_keeps_families():
    reg = Registry()
    c = reg.counter("t_total", "x")
    c.inc(5)
    reg.reset()
    assert reg.counter("t_total", "x") is c
    assert c.value == 0


def test_reset_preserves_cached_label_children():
    # instrumented objects (engine, batcher) resolve .labels() once and
    # keep the child; reset() must zero it IN PLACE, not orphan it
    reg = Registry()
    child = reg.counter("t_cached_total", "x", ("m",)).labels(m="lm")
    hchild = reg.histogram("t_cached_s", "x", ("m",),
                           buckets=(1,)).labels(m="lm")
    child.inc(3)
    hchild.observe(0.5)
    reg.reset()
    child.inc()  # the cached reference must still feed the render
    hchild.observe(0.5)
    samples = obs.parse_text(reg.render())
    assert obs.sample_value(samples, "t_cached_total", {"m": "lm"}) == 1
    assert obs.sample_value(samples, "t_cached_s_count", {"m": "lm"}) == 1


def test_unescape_backslash_then_n_roundtrips():
    reg = Registry()
    # literal backslash followed by literal 'n' — renders as \\n, which
    # a naive chained-replace unescape corrupts into backslash+newline
    reg.counter("t_esc_total", "x", ("p",)).labels(p="a\\nb").inc()
    (name, labels, value), = obs.parse_text(reg.render())
    assert labels["p"] == "a\\nb"


def test_render_values_formats():
    reg = Registry()
    g = reg.gauge("t_g", "g")
    g.set(0.25)
    samples = obs.parse_text(reg.render())
    assert obs.sample_value(samples, "t_g") == 0.25
    g.set(math.inf)
    samples = obs.parse_text(reg.render())
    assert obs.sample_value(samples, "t_g") == math.inf


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


def test_tracer_writes_ordered_jsonl(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with tracing.tracing(path) as tr:
        tracing.trace("r1", "queued", model="m")
        tracing.trace("r2", "queued", model="m")
        tracing.trace("r1", "complete", tokens=3)
        assert [r["span"] for r in tr.spans_for("r1")] \
            == ["queued", "complete"]
        seqs = [r["seq"] for r in tr.records]
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
    records = read_jsonl(path)  # same reader chain as train/workflow
    assert [r["span"] for r in records if r["request_id"] == "r1"] \
        == ["queued", "complete"]
    assert records[-1]["tokens"] == 3


def test_trace_is_noop_when_disarmed():
    tracing.trace("r1", "queued")  # must not raise, nothing installed
    assert tracing.active() is None


# ---------------------------------------------------------------------------
# HTTP layer: /metrics endpoint, route metrics, request-id stamping
# ---------------------------------------------------------------------------


class Echo(Model):
    def predict(self, payload):
        return {"predictions": payload.get("instances", []),
                "request_id": payload.get("request_id")}


@pytest.fixture
def server():
    srv = ModelServer([Echo("m")], host="127.0.0.1", port=0)
    srv.load_all()
    srv.start()
    yield srv
    srv.stop()


def _get(server, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{path}", timeout=10) as r:
            return r.status, r.headers.get("Content-Type"), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read()


def _post(server, payload, headers=None, path="/v1/models/m:predict"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_metrics_endpoint_serves_valid_exposition(server):
    _post(server, {"instances": ["a"]})
    _get(server, "/readyz")
    status, ctype, body = _get(server, "/metrics")
    assert status == 200
    assert ctype == obs.CONTENT_TYPE
    samples = obs.parse_text(body.decode())  # strict format validation
    assert obs.sample_value(samples, "kct_server_requests_total",
                            {"route": "predict", "method": "POST",
                             "status": "200"}) == 1
    assert obs.sample_value(samples, "kct_server_requests_total",
                            {"route": "readyz"}) == 1
    assert obs.sample_value(samples, "kct_server_request_seconds_count",
                            {"route": "predict"}) == 1
    # the scrape itself is counted too (visible on the NEXT scrape)
    _, _, body2 = _get(server, "/metrics")
    samples2 = obs.parse_text(body2.decode())
    assert obs.sample_value(samples2, "kct_server_requests_total",
                            {"route": "metrics"}) >= 1


def test_inbound_request_id_header_honored(server):
    with tracing.tracing():
        code, body = _post(server, {"instances": ["a"]},
                           headers={"X-Request-Id": "corr-123"})
    assert code == 200
    assert body["request_id"] == "corr-123"
    # without the header an id is minted
    code, body = _post(server, {"instances": ["a"]})
    assert body["request_id"]


def test_error_statuses_are_counted(server):
    _post(server, {"instances": ["a"]}, path="/v1/models/nope:predict")
    _, _, body = _get(server, "/metrics")
    samples = obs.parse_text(body.decode())
    assert obs.sample_value(samples, "kct_server_requests_total",
                            {"route": "predict", "status": "404"}) == 1


# ---------------------------------------------------------------------------
# chaos: a broken scrape never hurts the data plane or readiness
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_raising_metrics_render_is_contained(server):
    with faults.inject(FaultSpec("metrics.render", mode="raise",
                                 times=-1)):
        status, _, body = _get(server, "/metrics")
        assert status == 500
        assert b"metrics unavailable" in body
        # the data plane and readiness are untouched
        assert _post(server, {"instances": ["a"]})[0] == 200
        assert _get(server, "/readyz")[0] == 200
    assert _get(server, "/metrics")[0] == 200  # recovers when disarmed


@pytest.mark.chaos
def test_hanging_metrics_render_is_contained(server):
    with faults.inject(FaultSpec("metrics.render", mode="hang",
                                 delay_s=30.0)) as inj:
        scrape_done = threading.Event()

        def scrape():
            _get(server, "/metrics")
            scrape_done.set()

        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        time.sleep(0.05)  # scrape thread is now parked in the hang
        assert not scrape_done.is_set()
        # readiness and the data plane answer while the scrape hangs
        assert _get(server, "/readyz")[0] == 200
        assert _post(server, {"instances": ["a"]})[0] == 200
        inj.release()
        t.join(timeout=10)
        assert scrape_done.is_set()


# ---------------------------------------------------------------------------
# /readyz diagnostic body (supervised batcher; no accelerator needed)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_readyz_body_carries_diagnostics():
    m = BatchingModel("bm", lambda insts, params: list(insts),
                      BatcherConfig(max_batch_size=2, max_queue_size=8))
    m.load()
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.05))
    sup.watch(m)
    srv = ModelServer([m], host="127.0.0.1", port=0)
    srv.start()
    try:
        _, _, body = _get(srv, "/readyz")
        detail = json.loads(body)["models"]["bm"]
        assert detail["ok"] is True
        assert detail["circuit"] == "closed"
        assert detail["restarts"] == 0
        assert detail["queue_depth"] == 0
        assert isinstance(detail["heartbeat_age_s"], float)

        # kill the dispatcher via fault injection → supervisor restarts
        # it; the restart count must surface in the body
        with faults.inject(FaultSpec("dispatch", mode="raise")):
            time.sleep(0.1)  # dispatcher hits the armed site and dies
            sup.check_now()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            _, _, body = _get(srv, "/readyz")
            detail = json.loads(body)["models"]["bm"]
            if detail["ok"] and detail["restarts"] == 1:
                break
            time.sleep(0.02)
        assert detail["restarts"] == 1
        assert detail["circuit"] == "closed"
        # …and in the supervisor metric family, by cause
        samples = obs.parse_text(obs.render_text())
        assert obs.sample_value(samples, "kct_supervisor_restarts_total",
                                {"model": "bm", "cause": "crash"}) == 1
    finally:
        srv.stop()
        sup.stop()
        m.stop()


# ---------------------------------------------------------------------------
# batcher + workflow metric families
# ---------------------------------------------------------------------------


def test_batcher_records_batch_metrics():
    m = BatchingModel("bb", lambda insts, params: list(insts),
                      BatcherConfig(max_batch_size=4))
    m.load()
    try:
        with tracing.tracing() as tr:
            out = m.predict({"instances": ["a", "b"],
                             "request_id": "bat-1"})
        assert out["predictions"] == ["a", "b"]
        assert [r["span"] for r in tr.spans_for("bat-1")] \
            == ["queued", "dispatched", "complete"]
    finally:
        m.stop()
    samples = obs.parse_text(obs.render_text())
    assert obs.sample_value(samples, "kct_batcher_batches_total",
                            {"model": "bb"}) == 1
    assert obs.sample_value(samples, "kct_batcher_requests_total",
                            {"model": "bb"}) == 1
    assert obs.sample_value(samples, "kct_batcher_batch_size_sum",
                            {"model": "bb"}) == 2
    assert obs.sample_value(samples, "kct_batcher_dispatch_seconds_count",
                            {"model": "bb"}) == 1


def test_workflow_engine_records_step_metrics(tmp_path):
    from kubernetes_cloud_tpu.workflow.engine import WorkflowRun
    from kubernetes_cloud_tpu.workflow.spec import (
        RetryStrategy,
        Step,
        WorkflowSpec,
    )

    spec = WorkflowSpec(name="obs-wf", steps=[
        Step(name="ok", command=["true"]),
        Step(name="flaky", command=["false"], deps=["ok"],
             retry=RetryStrategy(limit=1, backoff=0.0)),
    ])
    run = WorkflowRun(spec, str(tmp_path / "wf"), sleep=lambda s: None)
    out = run.run()
    assert out["status"] == "failed"
    samples = obs.parse_text(obs.render_text())
    assert obs.sample_value(samples, "kct_workflow_step_seconds_count",
                            {"workflow": "obs-wf", "step": "ok"}) == 1
    assert obs.sample_value(samples, "kct_workflow_step_retries_total",
                            {"workflow": "obs-wf", "step": "flaky"}) == 1
    assert obs.sample_value(samples, "kct_workflow_transitions_total",
                            {"workflow": "obs-wf",
                             "state": "succeeded"}) == 1
    assert obs.sample_value(samples, "kct_workflow_transitions_total",
                            {"workflow": "obs-wf", "state": "failed"}) == 1


# ---------------------------------------------------------------------------
# load_test: TTFT stats + client-vs-server metrics cross-check
# ---------------------------------------------------------------------------


class TtftEcho(Model):
    def predict(self, payload):
        return {"predictions": [
            {"generated_text": "x", "tokens_out": 4, "ttft_s": 0.025}
            for _ in payload.get("instances", [])]}


def test_load_test_reports_ttft_and_checks_metrics(capsys):
    srv = ModelServer([TtftEcho("m")], host="127.0.0.1", port=0)
    srv.load_all()
    srv.start()
    try:
        stats = load_test.main([
            "--url", f"http://127.0.0.1:{srv.port}/v1/models/m:predict",
            "--requests", "6", "--concurrency", "3", "--check-metrics"])
    finally:
        srv.stop()
    assert stats["successful"] == 6
    assert stats["ttft_mean_s"] == pytest.approx(0.025)
    assert stats["ttft_p95_s"] == pytest.approx(0.025)
    assert stats["tokens_out_total"] == 24
    check = stats["metrics_check"]
    assert check == {"route": "predict", "client_requests": 6,
                     "client_responded": 6, "server_requests": 6,
                     "ok": True}


def test_load_test_metrics_check_fails_loudly():
    # a server whose histogram disagrees with the client count must
    # exit 2 — silent bookkeeping drift is the failure mode the flag
    # exists to catch
    srv = ModelServer([TtftEcho("m")], host="127.0.0.1", port=0)
    srv.load_all()
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/v1/models/m:predict"
    try:
        # prime one request BETWEEN the scrapes via a side channel the
        # client doesn't count: monkey-level — issue it inside the run
        # window by running a first request before the pre-scrape…
        before = load_test.scrape_metrics(
            load_test.metrics_endpoint(url))
        # …then two requests the "client" claims as one
        load_test._one_request(url, b'{"instances": ["a"]}', 10.0)
        load_test._one_request(url, b'{"instances": ["a"]}', 10.0)
        after = load_test.scrape_metrics(load_test.metrics_endpoint(url))
        check = load_test.check_metrics(before, after, url,
                                        client_count=1)
        assert check["ok"] is False
        assert check["server_requests"] == 2
        # with timeouts excused, a server count INSIDE the
        # [responded, attempted] window passes
        tolerant = load_test.check_metrics(before, after, url,
                                           client_count=2,
                                           client_responded=1)
        assert tolerant["ok"] is True
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# metric-family catalog completeness
# ---------------------------------------------------------------------------


def test_catalog_covers_every_registered_kct_family():
    # every kct_* family the process registers must have a catalog
    # entry (obs/catalog.py) — an instrumented-but-uncataloged family
    # is exactly the telemetry drift KCT-REG exists to kill.  Import
    # the serving layers that register at import time first; jax-free
    # by construction.
    import kubernetes_cloud_tpu.serve.autoscaler  # noqa: F401
    import kubernetes_cloud_tpu.serve.fleet  # noqa: F401
    from kubernetes_cloud_tpu.obs.catalog import METRIC_FAMILIES

    registered = {name for name in obs.REGISTRY._metrics
                  if name.startswith("kct_")}
    assert registered, "no kct_* families registered?"
    missing = registered - set(METRIC_FAMILIES)
    assert not missing, f"registered but not in catalog: {sorted(missing)}"


def test_autoscaler_families_cataloged_and_emitting():
    from kubernetes_cloud_tpu.obs.catalog import METRIC_FAMILIES
    from kubernetes_cloud_tpu.serve.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        PoolSignals,
        RolePolicy,
        ScalingTarget,
    )

    wanted = [
        "kct_autoscaler_desired_replicas",
        "kct_autoscaler_replicas",
        "kct_autoscaler_panic",
        "kct_autoscaler_cold_start_seconds",
        "kct_autoscaler_activator_queue_depth",
        "kct_autoscaler_scale_events_total",
    ]
    for name in wanted:
        assert name in METRIC_FAMILIES, name
        assert obs.REGISTRY.get(name) is not None, name

    class _Target(ScalingTarget):
        def roles(self):
            return ("colocated",)

        def signals(self, role):
            return PoolSignals(ready=1, concurrency=9.0, arrivals=5)

        def scale_up(self, role, n):
            return n

        def scale_down(self, role, n):
            return n

    cfg = AutoscalerConfig(
        roles={"colocated": RolePolicy(max_replicas=8,
                                       target_concurrency=2.0)})
    scaler = Autoscaler(_Target(), cfg, clock=lambda: 0.0)
    scaler.step(now=0.0)
    scaler.note_cold_start("colocated", 3.0)
    desired = obs.REGISTRY.get("kct_autoscaler_desired_replicas")
    assert desired.labels(role="colocated").value >= 1
    hist = obs.REGISTRY.get("kct_autoscaler_cold_start_seconds")
    assert hist.labels(role="colocated").count == 1
