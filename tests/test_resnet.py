"""ResNet family + vision trainer (reference resnet50 parity,
``kubeflow/training-operator/resnet50/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.data.images import synthetic_batches
from kubernetes_cloud_tpu.models.vision.resnet import (
    PRESETS,
    ResNetConfig,
    forward,
    init_params,
    topk_accuracy,
)
from kubernetes_cloud_tpu.train.vision_trainer import (
    VisionTrainConfig,
    evaluate,
    init_vision_state,
    make_eval_step,
    make_vision_train_step,
    train_epoch,
)

TINY = PRESETS["resnet-tiny"]


def test_forward_shapes_and_dtype():
    params, stats = init_params(TINY, jax.random.key(0))
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    logits, new_stats = forward(TINY, params, x, stats, train=False)
    assert logits.shape == (2, TINY.num_classes)
    assert logits.dtype == jnp.float32
    # eval mode must not touch running stats
    chex_equal = jax.tree.map(
        lambda a, b: bool(jnp.all(a == b)), stats, new_stats)
    assert all(jax.tree.leaves(chex_equal))


def test_bottleneck_param_count_resnet50():
    # torchvision resnet50 has 25,557,032 params; architectural golden.
    cfg = ResNetConfig(depth=50, num_classes=1000)
    params, _ = init_params(cfg, jax.random.key(0))
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == 25_557_032


def test_train_mode_updates_stats():
    params, stats = init_params(TINY, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    _, new_stats = forward(TINY, params, x, stats, train=True)
    assert not bool(jnp.all(new_stats["stem"]["bn"]["mean"]
                            == stats["stem"]["bn"]["mean"]))


def test_topk_accuracy():
    logits = jnp.array([[0.1, 0.9, 0.0, 0.0],
                        [0.9, 0.1, 0.0, 0.0],
                        [0.0, 0.1, 0.2, 0.9]])
    labels = jnp.array([1, 1, 0])
    acc = topk_accuracy(logits, labels, ks=(1, 3))
    assert acc["top1"] == pytest.approx(1 / 3)
    assert acc["top3"] == pytest.approx(2 / 3)


def test_synthetic_learning_and_eval(devices8):
    """Loss decreases and accuracy beats chance on the synthetic task —
    the golden-progress check standing in for ImageNet epochs."""
    mesh = build_mesh(MeshSpec(data=4, fsdp=2), devices=devices8)
    tcfg = VisionTrainConfig(learning_rate=0.05, world_scale=1,
                             steps_per_epoch=8, epochs=1)
    state = init_vision_state(TINY, tcfg, jax.random.key(0), mesh)
    step = jax.jit(make_vision_train_step(TINY, tcfg), donate_argnums=0)

    def batches(steps, seed):
        return synthetic_batches(16, image_size=32,
                                 num_classes=TINY.num_classes,
                                 steps=steps, seed=seed)

    state, summary = train_epoch(step, state, batches(12, 0), mesh=mesh)
    first_loss = summary["loss"]
    for epoch in range(1, 4):
        state, summary2 = train_epoch(step, state, batches(12, epoch),
                                      mesh=mesh)
    assert summary2["loss"] < first_loss

    eval_step = jax.jit(make_eval_step(TINY))
    metrics = evaluate(eval_step, state, batches(4, 2), mesh=mesh)
    assert metrics["top1"] > 1.5 / TINY.num_classes
    assert set(metrics) >= {"top1", "top5", "loss"}
