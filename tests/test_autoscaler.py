"""Elastic-autoscaler units: rolling digests, the activator's
hold/replay contract, KPA target-tracking math (panic entry, hysteresis
and cooldown, scale-to-zero, predictive pre-warming, cold-start EWMA),
the adaptive live-TTFT hedge delay, and the Knative-annotation mapping
the deploy docs promise.  Everything here is jax-free and
deterministic: the control loop runs on an explicit virtual ``now``,
never the wall clock."""

import threading
import time

import pytest

from kubernetes_cloud_tpu.serve.autoscaler import (
    KNATIVE_ANNOTATIONS,
    Activator,
    Autoscaler,
    AutoscalerConfig,
    PoolSignals,
    RolePolicy,
    RollingDigest,
    ScalingTarget,
)
from kubernetes_cloud_tpu.serve.fleet import FleetConfig, FleetRouter


# ---------------------------------------------------------------------------
# RollingDigest
# ---------------------------------------------------------------------------


def test_digest_quantile_windows_and_min_samples():
    d = RollingDigest(window_s=10.0)
    for i in range(10):
        d.observe(float(i), now=float(i))
    # window [0, 9]: everything in range
    assert d.quantile(0.0, now=9.0) == 0.0
    assert d.quantile(1.0, now=9.0) == 9.0
    assert d.quantile(0.5, now=9.0) == 5.0
    # advance: samples older than 10 s fall out
    assert d.quantile(0.0, now=15.0) == 5.0
    # below min_samples the digest abstains (hedging falls back to
    # the fixed floor, never a junk quantile)
    assert d.quantile(0.5, now=9.0, min_samples=100) is None
    assert RollingDigest(window_s=5.0).quantile(0.5) is None


def test_digest_trend_fits_slope():
    d = RollingDigest(window_s=60.0)
    for i in range(20):
        d.observe(2.0 * i + 1.0, now=float(i))
    fit, slope = d.trend(now=19.0)
    assert slope == pytest.approx(2.0, abs=1e-6)
    assert fit == pytest.approx(39.0, abs=1e-6)
    flat = RollingDigest(window_s=60.0)
    flat.observe(5.0, now=0.0)
    assert flat.trend(now=0.0) == (5.0, 0.0)


def test_digest_bounds_sample_count():
    d = RollingDigest(window_s=1e9, max_samples=100)
    for i in range(1000):
        d.observe(float(i), now=float(i))
    assert d.count(now=999.0) == 100
    assert d.quantile(0.0, now=999.0) == 900.0


def test_digest_validates():
    with pytest.raises(ValueError):
        RollingDigest(window_s=0)
    with pytest.raises(ValueError):
        RollingDigest(window_s=1.0).quantile(1.5)


# ---------------------------------------------------------------------------
# Activator
# ---------------------------------------------------------------------------


def test_activator_hold_replays_on_capacity():
    pokes = []
    act = Activator(max_hold_s=30.0, on_demand=lambda: pokes.append(1))
    got = []

    def waiter():
        got.append(act.hold())

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while act.depth == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert act.depth == 1
    assert pokes == [1]  # the park itself signalled demand
    act.notify_capacity()
    t.join(timeout=5.0)
    assert got == [True]
    assert act.depth == 0
    assert act.stats["held"] == 1 and act.stats["replayed"] == 1


def test_activator_hold_times_out():
    act = Activator(max_hold_s=30.0)
    t0 = time.monotonic()
    assert act.hold(deadline=t0 + 0.05) is False
    assert act.stats["timeouts"] == 1
    assert act.depth == 0


def test_activator_raising_demand_hook_is_contained():
    def boom():
        raise RuntimeError("hook down")

    act = Activator(max_hold_s=30.0, on_demand=boom)
    assert act.hold(deadline=time.monotonic() + 0.05) is False


# ---------------------------------------------------------------------------
# Autoscaler control loop (stub target, virtual clock)
# ---------------------------------------------------------------------------


class StubTarget(ScalingTarget):
    """Instant-capacity target: scale_up turns ready next signals
    read; every call is recorded for assertions."""

    def __init__(self, role="colocated", ready=1):
        self.role = role
        self.sig = PoolSignals(ready=ready)
        self.ups: list[int] = []
        self.downs: list[int] = []

    def roles(self):
        return (self.role,)

    def signals(self, role):
        assert role == self.role
        return self.sig

    def scale_up(self, role, n):
        self.ups.append(n)
        self.sig.ready += n
        return n

    def scale_down(self, role, n):
        self.downs.append(n)
        self.sig.ready -= n
        return n


def _cfg(**kw):
    role = kw.pop("role", "colocated")
    policy = kw.pop("policy", None) or RolePolicy(
        min_replicas=kw.pop("min_replicas", 1),
        max_replicas=kw.pop("max_replicas", 10),
        target_concurrency=kw.pop("target_concurrency", 2.0))
    base = dict(tick_s=1.0, stable_window_s=10.0, panic_window_s=3.0,
                panic_threshold=2.0, panic_hold_s=10.0,
                scale_down_delay_s=5.0, cooldown_s=2.0,
                scale_to_zero_grace_s=5.0, prewarm=False,
                roles={role: policy})
    base.update(kw)
    return AutoscalerConfig(**base)


def test_target_tracking_sizes_ceil_of_concurrency_over_target():
    tgt = StubTarget(ready=1)
    scaler = Autoscaler(tgt, _cfg(), clock=lambda: 0.0)
    tgt.sig.concurrency = 9.0
    out = scaler.step(now=0.0)
    assert out["colocated"]["desired"] == 5  # ceil(9 / 2)
    assert tgt.ups == [4]
    # steady state: no further scaling
    out = scaler.step(now=1.0)
    assert out["colocated"]["applied"] == 0


def test_max_replicas_clamps_and_max_step_bounds():
    tgt = StubTarget(ready=1)
    scaler = Autoscaler(
        tgt, _cfg(max_replicas=3, max_scale_up_step=1),
        clock=lambda: 0.0)
    tgt.sig.concurrency = 100.0
    scaler.step(now=0.0)
    assert tgt.ups == [1]  # one spawn per decision, clamped at 3
    scaler.step(now=1.0)
    assert tgt.ups == [1, 1]
    scaler.step(now=2.0)
    assert tgt.sig.ready == 3
    scaler.step(now=3.0)
    assert tgt.ups == [1, 1]  # at max_replicas: no further ups


def test_panic_mode_scales_on_burst_and_blocks_scale_down():
    tgt = StubTarget(ready=2)
    scaler = Autoscaler(tgt, _cfg(), clock=lambda: 0.0)
    # calm history holds the stable window at steady state (desired
    # == ready, so neither direction moves)
    tgt.sig.concurrency = 4.0
    for t in range(8):
        scaler.step(now=float(t))
    assert tgt.ups == [] and tgt.downs == []
    # burst: short panic window sees it immediately even though the
    # stable average is still diluted by the calm history
    tgt.sig.concurrency = 40.0
    out = scaler.step(now=8.0)
    assert out["colocated"]["in_panic"] is True
    assert tgt.sig.ready > 2
    assert scaler.stats["panics"] == 1
    # burst passes; panic holds — no scale-down inside panic_hold_s
    tgt.sig.concurrency = 0.0
    for t in range(9, 14):
        out = scaler.step(now=float(t))
        assert out["colocated"]["in_panic"] is True
    assert tgt.downs == []


def test_scale_down_needs_delay_and_cooldown():
    tgt = StubTarget(ready=6)
    cfg = _cfg(scale_down_delay_s=5.0, cooldown_s=2.0)
    scaler = Autoscaler(tgt, cfg, clock=lambda: 0.0)
    tgt.sig.concurrency = 2.0  # desired = 1, surplus of 5
    scaler.step(now=0.0)
    assert tgt.downs == []  # surplus must persist first
    scaler.step(now=3.0)
    assert tgt.downs == []
    scaler.step(now=5.0)  # delay satisfied, cooldown clear
    assert tgt.downs == [5]
    assert tgt.sig.ready == 1


def test_flapping_surplus_resets_hysteresis():
    tgt = StubTarget(ready=4)
    scaler = Autoscaler(tgt, _cfg(stable_window_s=2.0,
                                  panic_window_s=1.0,
                                  scale_down_delay_s=5.0,
                                  cooldown_s=0.0),
                        clock=lambda: 0.0)
    tgt.sig.concurrency = 2.0  # desired 1: surplus of 3 opens
    scaler.step(now=0.0)
    scaler.step(now=2.0)
    # load returns before the delay elapses: the below-clock resets
    tgt.sig.concurrency = 14.0  # short-window mean 8 -> desired 4
    scaler.step(now=4.0)
    # surplus reopens: the 5 s clock must restart from here, so no
    # scale-down until a CONTINUOUS surplus stretch elapses
    tgt.sig.concurrency = 2.0
    scaler.step(now=6.0)
    scaler.step(now=8.0)
    scaler.step(now=10.0)
    scaler.step(now=12.0)
    assert tgt.downs == []  # never 5 continuous surplus seconds yet
    scaler.step(now=13.0)  # 13 - 8 = 5: the continuous stretch lands
    assert tgt.downs == [3]


def test_scale_to_zero_after_grace_and_activator_forces_one():
    tgt = StubTarget(ready=1)
    scaler = Autoscaler(tgt, _cfg(min_replicas=0,
                                  scale_to_zero_grace_s=5.0,
                                  scale_down_delay_s=0.0,
                                  cooldown_s=0.0),
                        clock=lambda: 0.0)
    tgt.sig.concurrency = 0.0
    for t in range(5):
        scaler.step(now=float(t))
    assert tgt.downs == []  # idle but inside the grace period
    out = scaler.step(now=5.0)
    assert out["colocated"]["desired"] == 0
    assert tgt.sig.ready == 0
    # a held arrival IS demand: the activator depth forces >= 1
    tgt.sig.activator_depth = 1
    out = scaler.step(now=6.0)
    assert out["colocated"]["desired"] >= 1
    assert tgt.sig.ready == 1


def test_prewarm_scales_ahead_of_rising_arrival_rate():
    tgt = StubTarget(ready=1)
    cfg = _cfg(prewarm=True, trend_window_s=10.0,
               cold_start_prior_s=10.0, target_concurrency=2.0)
    scaler = Autoscaler(tgt, cfg, clock=lambda: 0.0)
    tgt.sig.concurrency = 2.0  # desired stays 1 on its own
    arrivals = 0
    for t in range(8):
        # arrival RATE doubles every couple of ticks: the linear fit
        # projects well past current demand one cold-start out
        arrivals += 4 * (t + 1)
        tgt.sig.arrivals = arrivals
        scaler.step(now=float(t))
    assert scaler.stats["prewarm_ups"] >= 1
    assert tgt.sig.ready > 1


def test_cold_start_prior_ewma_tracks_measurements():
    tgt = StubTarget()
    scaler = Autoscaler(tgt, _cfg(cold_start_prior_s=10.0),
                        clock=lambda: 0.0)
    assert scaler.cold_start_s("colocated") == 10.0  # the prior
    scaler.note_cold_start("colocated", 4.0)
    assert scaler.cold_start_s("colocated") == 4.0  # first = seed
    scaler.note_cold_start("colocated", 8.0)
    # alpha = 0.4: 0.4*8 + 0.6*4
    assert scaler.cold_start_s("colocated") == pytest.approx(5.6)


def test_kick_wakes_the_loop_thread():
    tgt = StubTarget(ready=0)
    scaler = Autoscaler(tgt, _cfg(tick_s=30.0, min_replicas=0),
                        clock=time.monotonic)
    tgt.sig.activator_depth = 1
    scaler.start()
    try:
        scaler.kick()
        deadline = time.monotonic() + 5.0
        while not tgt.ups and time.monotonic() < deadline:
            time.sleep(0.01)
        assert tgt.ups  # the kick ran a tick well before tick_s
    finally:
        scaler.stop()


def test_config_validation():
    with pytest.raises(ValueError):
        RolePolicy(min_replicas=-1)
    with pytest.raises(ValueError):
        RolePolicy(min_replicas=5, max_replicas=2)
    with pytest.raises(ValueError):
        RolePolicy(target_concurrency=0)
    with pytest.raises(ValueError):
        AutoscalerConfig(panic_window_s=60.0, stable_window_s=30.0)
    with pytest.raises(ValueError):
        AutoscalerConfig(panic_threshold=0.5)
    with pytest.raises(ValueError):
        AutoscalerConfig(roles={"nonsense": RolePolicy()})
    with pytest.raises(ValueError):
        AutoscalerConfig(roles={"prefill": "not-a-policy"})


def test_knative_annotation_map_names_real_fields():
    # the deploy/README migration table is generated from this map —
    # every target must be a real config field (or the activator)
    cfg_fields = {f.name for f in
                  AutoscalerConfig.__dataclass_fields__.values()}
    pol_fields = {f.name for f in
                  RolePolicy.__dataclass_fields__.values()}
    for annotation, target in KNATIVE_ANNOTATIONS.items():
        assert annotation.startswith("autoscaling.knative.dev/")
        if target.startswith("AutoscalerConfig."):
            assert target.split(".", 1)[1] in cfg_fields, target
        elif target.startswith("RolePolicy."):
            assert target.split(".", 1)[1] in pol_fields, target
        else:
            assert "Activator" in target


# ---------------------------------------------------------------------------
# adaptive hedge delay (fleet.py satellite)
# ---------------------------------------------------------------------------


def _empty_router(**cfg_kw):
    fcfg = FleetConfig(**cfg_kw)
    return FleetRouter([], fcfg, host="127.0.0.1", port=0,
                       allow_empty=True)


def test_empty_fleet_requires_opt_in():
    with pytest.raises(ValueError):
        FleetRouter([], FleetConfig(), host="127.0.0.1", port=0)


def test_hedge_delay_floors_at_fixed_knob():
    router = _empty_router(hedge_after_s=0.5, hedge_ttft_quantile=0.9,
                           hedge_ttft_factor=2.0,
                           hedge_ttft_min_samples=4)
    # cold digest: the fixed knob alone
    assert router._hedge_delay("colocated") == 0.5
    digest = RollingDigest(window_s=60.0)
    router._ttft_digests["colocated"] = digest
    # thin digest (below min_samples): still the floor
    digest.observe(10.0)
    assert router._hedge_delay("colocated") == 0.5
    # warm digest, fast TTFTs: quantile*factor below the floor — the
    # floor wins (backward compat: never hedge EARLIER than the knob)
    for _ in range(10):
        digest.observe(0.01)
    assert router._hedge_delay("colocated") == 0.5
    # slow TTFTs: the adaptive delay takes over
    for _ in range(20):
        digest.observe(1.0)
    assert router._hedge_delay("colocated") == pytest.approx(2.0)


def test_hedge_disabled_stays_disabled_regardless_of_digest():
    router = _empty_router(hedge_after_s=None)
    digest = RollingDigest(window_s=60.0)
    for _ in range(50):
        digest.observe(3.0)
    router._ttft_digests["colocated"] = digest
    assert router._hedge_delay("colocated") is None


def test_hedge_quantile_none_falls_back_to_fixed():
    router = _empty_router(hedge_after_s=0.25,
                           hedge_ttft_quantile=None)
    digest = RollingDigest(window_s=60.0)
    for _ in range(50):
        digest.observe(3.0)
    router._ttft_digests["colocated"] = digest
    assert router._hedge_delay("colocated") == 0.25


def test_observe_ttft_is_per_role():
    router = _empty_router(hedge_after_s=0.1,
                           hedge_ttft_min_samples=1,
                           hedge_ttft_factor=1.0,
                           hedge_ttft_quantile=1.0)

    class _R:
        pass

    rep = _R()
    rep.health = _R()
    rep.health.role = "prefill"
    router._observe_ttft(rep, {"predictions": [{"ttft_s": 4.0},
                                               {"ttft_s": 2.0}]})
    assert router._hedge_delay("prefill") == pytest.approx(4.0)
    # other roles' digests are untouched — colocated stays at floor
    assert router._hedge_delay("colocated") == pytest.approx(0.1)
    # bodies without predictions are ignored, not an error
    router._observe_ttft(rep, {"error": "nope"})


def test_fleet_config_validates_hedge_ttft_knobs():
    with pytest.raises(ValueError):
        FleetConfig(hedge_ttft_quantile=1.5)
    with pytest.raises(ValueError):
        FleetConfig(hedge_ttft_factor=0.0)
    with pytest.raises(ValueError):
        FleetConfig(hedge_ttft_min_samples=0)


def test_supervisor_capacity_hook_pokes_and_is_contained():
    """serve/supervisor.py's capacity hook: unset is a no-op, a wired
    hook fires, and a raising hook never takes the watchdog down."""
    from kubernetes_cloud_tpu.serve.supervisor import ServingSupervisor

    sup = ServingSupervisor()
    sup._notify_capacity_change()  # no hook wired: no-op
    calls = []
    sup.on_capacity_change = lambda: calls.append(1)
    sup._notify_capacity_change()
    assert calls == [1]

    def boom():
        raise RuntimeError("kick failed")

    sup.on_capacity_change = boom
    sup._notify_capacity_change()  # contained, not raised
