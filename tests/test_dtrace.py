"""Distributed-trace unit lane (``obs/dtrace.py``, jax-free): wire
parsing (plain / W3C / flags / garbage), the bounded span store and
its eviction order, request-id binding with engine-suffix stripping,
tail-sampling decide semantics, exemplars, span merging, and the
critical-path analyzer's edge attribution."""

import pytest

from kubernetes_cloud_tpu.obs import dtrace


@pytest.fixture()
def st():
    """A fresh process store per test; the previous store object is
    restored afterward so module-scoped servers in other files keep
    their bindings."""
    prev = dtrace.store()
    store = dtrace.reset(head_sample=1.0)
    yield store
    dtrace._STORE = prev


# -- wire format -------------------------------------------------------------

def test_wire_roundtrip_plain(st):
    ctx = dtrace.mint()
    parsed = dtrace.parse(ctx.wire())
    assert parsed is not None
    assert parsed.trace_id == ctx.trace_id
    assert parsed.parent_id == ctx.span_id  # callee parents the caller
    assert parsed.span_id != ctx.span_id    # own span, freshly minted
    assert parsed.caller_decides is False   # plain client mint


def test_child_wire_claims_sampling_authority(st):
    ctx = dtrace.mint()
    leg = dtrace.new_span_id()
    parsed = dtrace.parse(ctx.child_wire(leg))
    assert parsed.trace_id == ctx.trace_id
    assert parsed.parent_id == leg
    assert parsed.caller_decides is True  # the -01 flags token


def test_parse_w3c_versioned_form(st):
    tid, sid = "ab" * 16, "cd" * 8
    parsed = dtrace.parse(f"00-{tid}-{sid}-01")
    assert (parsed.trace_id, parsed.parent_id) == (tid, sid)
    assert parsed.caller_decides is True
    # without flags the version prefix still drops
    parsed = dtrace.parse(f"00-{tid}-{sid}")
    assert parsed.trace_id == tid and parsed.caller_decides is False


@pytest.mark.parametrize("garbage", [
    None, "", "nonsense", "not-hex-!!-stuff", "deadbeef",      # 1 token
    "zzzzzzzzzzzz-zzzzzzzzzzzz",                               # non-hex
    "deadbeef-cafe",                                           # too short
    "x" * 200,                                                 # too long
    42,                                                        # non-str
])
def test_parse_garbage_returns_none(st, garbage):
    assert dtrace.parse(garbage) is None


# -- bindings ----------------------------------------------------------------

def test_binding_strips_engine_suffixes(st):
    ctx = dtrace.mint()
    dtrace.bind("rid", ctx)
    # per-instance and hedge-leg ids the door never bound resolve to
    # the base binding (up to 3 trailing segments stripped)
    for rid in ("rid", "rid-0", "rid-h", "rid-h-0"):
        assert dtrace.context_for(rid) is ctx
    assert dtrace.context_for("other") is None
    assert dtrace.context_for(None) is None
    assert dtrace.unbind("rid") is ctx
    assert dtrace.context_for("rid") is None


def test_conditional_unbind_respects_the_rebinding_owner(st):
    """In-process replicas REBIND a request id over the router's
    binding in the shared store; the router's door exit must not
    strip the replica's binding (and vice versa)."""
    router_ctx, replica_ctx = dtrace.mint(), dtrace.mint()
    dtrace.bind("rid", router_ctx)
    dtrace.bind("rid", replica_ctx)  # the replica door rebinds
    assert dtrace.unbind("rid", router_ctx) is None  # not the owner
    assert dtrace.context_for("rid") is replica_ctx
    assert dtrace.unbind("rid", replica_ctx) is replica_ctx
    assert dtrace.unbind("rid", replica_ctx) is None  # already gone


# -- bounded store -----------------------------------------------------------

def test_span_cap_per_trace(st):
    st.max_spans = 3
    for i in range(5):
        st.add_span("t1", f"s{i}", None, "decode")
    assert len(st.spans_for("t1")) == 3


def test_eviction_prefers_undecided_boring_traces(st):
    st.max_traces = 4
    st.add_span("keepme", "s0", None, "server")
    st.note_keep("keepme", "hedged")
    for i in range(10):
        st.add_span(f"boring{i}", "s0", None, "server")
    assert st.spans_for("keepme") is not None  # survived the burst
    assert len(st.index(last=100)) == 4        # bound held


def test_disabled_store_is_inert(st):
    st.enabled = False
    st.bind("rid", dtrace.mint())
    st.add_span("t1", "s1", None, "server")
    st.note_keep("t1", "hedged")
    assert st.context_for("rid") is None
    assert st.spans_for("t1") is None


def test_configure_rejects_unknown_keys(st):
    with pytest.raises(ValueError, match="unknown dtrace option"):
        dtrace.configure(max_tracez=7)
    assert dtrace.configure(max_traces=7).max_traces == 7


# -- tail sampling -----------------------------------------------------------

def test_decide_drops_boring_and_deletes(st):
    st.head_sample = 0.0
    st.add_span("t1", "s1", None, "server")
    assert st.decide("t1") == "dropped"
    assert st.spans_for("t1") is None  # dropped = gone
    assert st.decide("unknown") is None


def test_decide_keeps_tail_reasons_and_is_idempotent(st):
    st.head_sample = 0.0
    st.add_span("t1", "s1", None, "server")
    st.note_keep("t1", "retried")
    assert st.decide("t1") == "kept_tail"
    assert st.decide("t1") == "kept_tail"  # retries re-enter safely
    assert st.spans_for("t1")
    assert st.keep_reasons("t1") == {"retried"}


def test_decide_head_samples_the_boring(st):
    st.head_sample = 1.0
    st.add_span("t1", "s1", None, "server")
    assert st.decide("t1") == "kept_head"
    assert st.spans_for("t1")


def test_auto_keep_from_engine_events(st):
    st.ttft_target_s = 0.5
    st.inter_token_target_s = 0.1
    cases = [
        ("preempted", {}, "preempted"),
        ("failed", {}, "error"),
        ("requeued", {}, "transplanted"),
        ("first_token", {"ttft_s": 0.9}, "slo_ttft"),
        # decode (2.0 - 0.2) / 9 tokens = 0.2 s/token > 0.1 target
        ("complete", {"duration_s": 2.0, "tokens": 10, "ttft_s": 0.2},
         "slo_inter_token"),
    ]
    for i, (span, fields, reason) in enumerate(cases):
        rid = f"r{i}"
        ctx = dtrace.mint()
        st.bind(rid, ctx)
        ids = st.on_event(rid, span, fields)
        assert ids["trace_id"] == ctx.trace_id
        assert ids["parent_id"] == ctx.span_id
        assert reason in st.keep_reasons(ctx.trace_id), span


def test_auto_keep_not_fired_under_target(st):
    st.ttft_target_s = 2.0
    ctx = dtrace.mint()
    st.bind("r", ctx)
    st.on_event("r", "first_token", {"ttft_s": 0.01})
    assert st.keep_reasons(ctx.trace_id) == set()


def test_on_event_without_binding_is_free(st):
    assert st.on_event("nobody", "queued", {}) is None
    assert st.index(last=10) == []


# -- exemplars ---------------------------------------------------------------

def test_exemplars_worst_first_truncated(st):
    for i in range(8):
        st.note_exemplar("ttft", float(i), f"t{i}", keep=5)
    got = st.exemplars()["ttft"]
    assert [e["trace_id"] for e in got] == ["t7", "t6", "t5", "t4", "t3"]
    assert got[0]["value"] == 7.0


# -- merge + waterfall -------------------------------------------------------

def test_merge_spans_dedups_and_orders(st):
    a = {"trace_id": "t", "span_id": "a", "parent_id": None,
         "name": "server", "ts": 2.0}
    b = {"trace_id": "t", "span_id": "b", "parent_id": "a",
         "name": "queued", "ts": 1.0}
    merged = dtrace.merge_spans([a, dict(a), b, dict(b)])
    assert [s["span_id"] for s in merged] == ["b", "a"]  # ts order


def test_render_waterfall_tree(st):
    spans = [
        {"trace_id": "t", "span_id": "a", "parent_id": None,
         "name": "server", "ts": 100.0, "dur_s": 0.5, "status": 200},
        {"trace_id": "t", "span_id": "b", "parent_id": "a",
         "name": "prefill", "ts": 100.1, "model": "lm"},
    ]
    out = dtrace.render_waterfall(spans)
    assert "server" in out and "prefill" in out
    assert "status=200" in out and "model=lm" in out
    assert dtrace.render_waterfall([]) == "(no spans)"


# -- critical path -----------------------------------------------------------

def _hedged_trace():
    """Synthetic assembled tree: root server span, a cancelled primary
    leg, a winning hedge leg whose engine saw queue → admit → first
    token → complete plus a KV handoff."""
    t0 = 1000.0
    spans = [
        {"span_id": "root", "parent_id": None, "name": "server",
         "ts": t0, "dur_s": 1.0, "status": 200},
        {"span_id": "leg_p", "parent_id": "root", "name": "dispatch",
         "ts": t0 + 0.01, "dur_s": 0.15, "leg": "primary",
         "outcome": "cancelled", "replica": "r0", "retry": 0},
        {"span_id": "leg_h", "parent_id": "root", "name": "dispatch",
         "ts": t0 + 0.11, "dur_s": 0.8, "leg": "hedge",
         "outcome": "win", "replica": "r1", "retry": 0},
        {"span_id": "rs", "parent_id": "leg_h", "name": "server",
         "ts": t0 + 0.12, "dur_s": 0.78},
        {"span_id": "q", "parent_id": "rs", "name": "queued",
         "ts": t0 + 0.12},
        {"span_id": "ad", "parent_id": "rs", "name": "admitted",
         "ts": t0 + 0.20},
        {"span_id": "kv", "parent_id": "rs", "name": "kv_transfer",
         "ts": t0 + 0.30, "dur_s": 0.05},
        {"span_id": "ft", "parent_id": "rs", "name": "first_token",
         "ts": t0 + 0.50},
        {"span_id": "cp", "parent_id": "rs", "name": "complete",
         "ts": t0 + 0.95},
    ]
    for s in spans:
        s["trace_id"] = "t"
    return spans


def test_analyze_attributes_edges_and_dominant(st):
    got = dtrace.analyze(_hedged_trace())
    edges = got["edges"]
    assert edges["router_queue"] == pytest.approx(0.01, abs=1e-6)
    assert edges["hedge_wait"] == pytest.approx(0.10, abs=1e-6)
    assert edges["tenant_queue"] == pytest.approx(0.08, abs=1e-6)
    assert edges["kv_transfer"] == pytest.approx(0.05, abs=1e-6)
    # prefill (admit -> first token) minus the KV window inside it
    assert edges["prefill"] == pytest.approx(0.25, abs=1e-6)
    assert edges["decode"] == pytest.approx(0.45, abs=1e-6)
    assert got["dominant"] == "decode"
    assert got["total_s"] == pytest.approx(1.0, abs=1e-6)
    assert got["spans"] == len(_hedged_trace())


def test_analyze_winner_path_excludes_loser_and_counts_retries(st):
    """Engine spans under a failed leg never pollute the attribution;
    failed-leg wall time lands in retry_amplification."""
    t0 = 1000.0
    spans = [
        {"span_id": "root", "parent_id": None, "name": "server",
         "ts": t0, "dur_s": 1.0, "status": 200},
        {"span_id": "leg0", "parent_id": "root", "name": "dispatch",
         "ts": t0 + 0.01, "dur_s": 0.4, "leg": "primary",
         "outcome": "error", "retry": 0},
        # the dead replica got as far as admitting before it crashed
        {"span_id": "q0", "parent_id": "leg0", "name": "queued",
         "ts": t0 + 0.02},
        {"span_id": "a0", "parent_id": "leg0", "name": "admitted",
         "ts": t0 + 0.03},
        {"span_id": "leg1", "parent_id": "root", "name": "dispatch",
         "ts": t0 + 0.45, "dur_s": 0.5, "leg": "primary",
         "outcome": "ok", "retry": 1},
        {"span_id": "q1", "parent_id": "leg1", "name": "queued",
         "ts": t0 + 0.46},
        {"span_id": "a1", "parent_id": "leg1", "name": "admitted",
         "ts": t0 + 0.56},
        {"span_id": "f1", "parent_id": "leg1", "name": "first_token",
         "ts": t0 + 0.66},
        {"span_id": "c1", "parent_id": "leg1", "name": "complete",
         "ts": t0 + 0.9},
    ]
    for s in spans:
        s["trace_id"] = "t"
    got = dtrace.analyze(spans)
    assert got["edges"]["retry_amplification"] == pytest.approx(0.4)
    # tenant_queue measured on the WINNING leg (0.10), not the dead one
    assert got["edges"]["tenant_queue"] == pytest.approx(0.10, abs=1e-6)
    assert "hedge_wait" not in got["edges"]  # retries are not hedges


def test_analyze_empty():
    assert dtrace.analyze([]) == {"edges": {}, "dominant": None,
                                  "total_s": 0.0, "spans": 0}
