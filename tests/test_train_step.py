import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.core import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models import PRESETS
from kubernetes_cloud_tpu.parallel import shard_batch
from kubernetes_cloud_tpu.train import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

CFG = PRESETS["test-tiny"]
TCFG = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=50)


def _batch(key, n=8, s=32):
    ids = jax.random.randint(key, (n, s), 0, CFG.vocab_size)
    return {"input_ids": ids, "attention_mask": jnp.ones_like(ids)}


def test_loss_decreases_single_device():
    state = init_train_state(CFG, TCFG, jax.random.key(0))
    step = jax.jit(make_train_step(CFG, TCFG), donate_argnums=0)
    batch = _batch(jax.random.key(1))
    first = None
    for _ in range(20):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first - 0.5, (
        f"loss did not decrease: {first} -> {float(metrics['loss'])}")
    assert int(state["step"]) == 20
    assert np.isfinite(float(metrics["grad_norm"]))


def test_sharded_training_matches_single_device(devices8):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2), devices=devices8)
    batch = _batch(jax.random.key(1))

    state1 = init_train_state(CFG, TCFG, jax.random.key(0))
    step1 = jax.jit(make_train_step(CFG, TCFG))
    state8 = init_train_state(CFG, TCFG, jax.random.key(0), mesh)
    step8 = jax.jit(make_train_step(CFG, TCFG))

    sbatch = shard_batch(batch, mesh)
    for _ in range(3):
        state1, m1 = step1(state1, batch)
        state8, m8 = step8(state8, sbatch)
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=1e-3)


def test_opt_state_is_sharded(devices8):
    mesh = build_mesh(MeshSpec(data=1, fsdp=4, model=2), devices=devices8)
    state = init_train_state(CFG, TCFG, jax.random.key(0), mesh)
    # adam mu for the qkv kernel must be sharded like the kernel itself
    leaves = jax.tree.leaves(
        state["opt_state"],
        is_leaf=lambda x: hasattr(x, "sharding") and x.ndim >= 2)
    big = [x for x in leaves if hasattr(x, "sharding") and x.ndim >= 3]
    assert any(
        any(s is not None for s in x.sharding.spec) for x in big
    ), "no optimizer leaf is sharded"
