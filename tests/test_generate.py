import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.models import PRESETS, forward, init_params
from kubernetes_cloud_tpu.models.generate import (
    decode_step,
    generate,
    init_cache,
    prefill,
    sample_token,
)

CFG = dataclasses.replace(PRESETS["test-tiny"], dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_prefill_matches_forward(params):
    ids = jax.random.randint(jax.random.key(1), (2, 10), 0, CFG.vocab_size)
    mask = jnp.ones_like(ids)
    full = forward(CFG, params, ids)
    last, _ = prefill(CFG, params, ids, mask, init_cache(CFG, 2, 16))
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               atol=1e-5)


def test_decode_matches_forward(params):
    ids = jax.random.randint(jax.random.key(1), (2, 10), 0, CFG.vocab_size)
    mask = jnp.ones_like(ids)
    full = forward(CFG, params, ids)
    _, cache = prefill(CFG, params, ids, mask, init_cache(CFG, 2, 16))
    tok = full[:, -1].argmax(-1).astype(jnp.int32)
    dec, cache = decode_step(CFG, params, tok, cache)
    ext = forward(CFG, params, jnp.concatenate([ids, tok[:, None]], 1))
    np.testing.assert_allclose(np.asarray(dec), np.asarray(ext[:, -1]),
                               atol=1e-4)
    assert int(cache["length"][0]) == 11


@pytest.mark.parametrize("variant", ["alibi", "learned"])
def test_decode_matches_forward_other_positions(variant):
    overrides = {
        "alibi": dict(pos_emb="alibi", parallel_residual=False,
                      embed_layernorm=True, tie_embeddings=True),
        "learned": dict(pos_emb="learned", parallel_residual=False,
                        tie_embeddings=True),
    }[variant]
    cfg = dataclasses.replace(CFG, **overrides)
    p = init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)
    mask = jnp.ones_like(ids)
    full = forward(cfg, p, ids)
    _, cache = prefill(cfg, p, ids, mask, init_cache(cfg, 2, 12))
    tok = full[:, -1].argmax(-1).astype(jnp.int32)
    dec, _ = decode_step(cfg, p, tok, cache)
    ext = forward(cfg, p, jnp.concatenate([ids, tok[:, None]], 1))
    np.testing.assert_allclose(np.asarray(dec), np.asarray(ext[:, -1]),
                               atol=1e-4)


def test_greedy_generate_matches_iterated_forward(params):
    ids = jax.random.randint(jax.random.key(1), (1, 6), 0, CFG.vocab_size)
    out = generate(CFG, params, ids, max_new_tokens=4, temperature=0.0)
    cur = ids
    for _ in range(4):
        nxt = forward(CFG, params, cur)[:, -1].argmax(-1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_generate_ragged_prompts(params):
    ids = jax.random.randint(jax.random.key(1), (2, 10), 1, CFG.vocab_size)
    mask = jnp.ones_like(ids).at[1, 6:].set(0)
    out = generate(CFG, params, ids, mask, max_new_tokens=3,
                   temperature=0.0, pad_token_id=0)
    # row 1's completion starts right after its 6 real tokens
    np.testing.assert_array_equal(np.asarray(out[1, :6]),
                                  np.asarray(ids[1, :6]))
    assert (np.asarray(out[1, 6:9]) != 0).all()


def test_eos_stops_row(params):
    ids = jax.random.randint(jax.random.key(1), (1, 4), 1, CFG.vocab_size)
    # force eos to be whatever greedy emits first -> generation stops
    first = generate(CFG, params, ids, max_new_tokens=1, temperature=0.0)
    eos = int(first[0, 4])
    out = generate(CFG, params, ids, max_new_tokens=5, temperature=0.0,
                   eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out[0, 5:]),
                                  np.zeros(4, np.int32))


def test_sample_token_top_k():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]])
    for seed in range(5):
        tok = sample_token(logits, jax.random.key(seed), temperature=1.0,
                           top_k=2, top_p=1.0)
        assert int(tok[0]) in (2, 3)


def test_sample_token_greedy():
    logits = jnp.asarray([[0.0, 5.0, 2.0]])
    tok = sample_token(logits, jax.random.key(0), temperature=0.0,
                       top_k=0, top_p=1.0)
    assert int(tok[0]) == 1
