"""Multi-tenant traffic plane: units + engine contract.

Covers serve/tenancy.py in isolation (token buckets, identity
resolution, the weighted-fair-queueing drain with quotas and the
virtual-time floor, victim selection) and threaded through the engine
(per-tenant classification, quota 503s with a Retry-After hint,
aggregated queue depth, config loading) plus the trace-replay schema
(the ``load_test.py --trace`` interchange format and its canned
fixture) — the quick-lane half; the preemption / monopolization /
containment proofs live in tests/test_tenancy_chaos.py.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve import trace as trace_mod
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    EngineConfig,
    GenRequest,
    load_engine_config,
)
from kubernetes_cloud_tpu.serve.errors import TenantQuotaError
from kubernetes_cloud_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    TenancyConfig,
    TenantScheduler,
    TenantSpec,
    TokenBucket,
    parse_tenancy,
)

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "tenant_trace.jsonl")


# -- token bucket ------------------------------------------------------------


def test_bucket_unlimited_when_rate_zero():
    b = TokenBucket(0.0)
    for _ in range(1000):
        assert b.try_take(50.0) == 0.0


def test_bucket_burst_then_refuses_with_refill_hint():
    now = 100.0
    b = TokenBucket(rate=2.0, burst=4.0, now=now)
    for _ in range(4):
        assert b.try_take(1.0, now=now) == 0.0
    wait = b.try_take(1.0, now=now)
    assert wait == pytest.approx(0.5, rel=0.01)  # 1 token / 2 per s
    # nothing was taken on refusal; half a second refills one token
    assert b.try_take(1.0, now=now + 0.5) == 0.0


def test_bucket_refill_caps_at_burst():
    now = 0.0
    b = TokenBucket(rate=10.0, burst=3.0, now=now)
    assert b.try_take(3.0, now=now) == 0.0
    # an hour of refill still only holds `burst`
    assert b.try_take(4.0, now=now + 3600.0) > 0.0
    assert b.try_take(3.0, now=now + 3600.0) == 0.0


# -- config / identity -------------------------------------------------------


def test_spec_validation():
    with pytest.raises(ValueError, match="lane"):
        TenantSpec("a", lane="bulk")
    with pytest.raises(ValueError, match="weight"):
        TenantSpec("a", weight=0.0)
    with pytest.raises(ValueError, match="req_rate"):
        TenantSpec("a", req_rate=-1.0)


def test_config_validation():
    with pytest.raises(ValueError, match="duplicate"):
        TenancyConfig(tenants=(TenantSpec("a"), TenantSpec("a")))
    with pytest.raises(ValueError, match="default"):
        TenancyConfig(tenants=(TenantSpec(DEFAULT_TENANT),))
    with pytest.raises(ValueError, match="api key"):
        TenancyConfig(tenants=(TenantSpec("a", api_keys=("k",)),
                               TenantSpec("b", api_keys=("k",))))


def test_resolution_ladder():
    cfg = TenancyConfig(tenants=(
        TenantSpec("acme", api_keys=("k-acme",)),
        TenantSpec("zeta"),
    ))
    assert cfg.resolve(tenant="acme").name == "acme"
    assert cfg.resolve(api_key="k-acme").name == "acme"
    assert cfg.resolve(api_key="zeta").name == "zeta"  # key == name
    assert cfg.resolve(tenant="nope").name == DEFAULT_TENANT
    assert cfg.resolve(api_key="nope").name == DEFAULT_TENANT
    assert cfg.resolve().name == DEFAULT_TENANT
    # the API key is the credential: it beats the payload label, and a
    # BAD key cannot be laundered into a configured tenant by the
    # payload (impersonation would drain the victim's buckets)
    assert cfg.resolve(tenant="zeta", api_key="k-acme").name == "acme"
    assert cfg.resolve(tenant="acme", api_key="nope").name \
        == DEFAULT_TENANT
    # name-as-key works ONLY for keyless tenants: a tenant with
    # configured secret keys is not reachable by its (public) name
    assert cfg.resolve(api_key="acme").name == DEFAULT_TENANT


def test_parse_tenancy_schema():
    assert parse_tenancy(None) is None
    assert parse_tenancy({}) is None
    cfg = parse_tenancy({
        "preemption": False,
        "max_preempt_per_step": 1,
        "min_batch_progress": 8,
        "default": {"weight": 2, "req_rate": 5},
        "tenants": [{"name": "acme", "weight": 4, "lane": "batch",
                     "api_keys": ["k1", "k2"], "token_rate": 1000}],
    })
    assert cfg.preemption is False
    assert cfg.max_preempt_per_step == 1
    assert cfg.min_batch_progress == 8
    assert cfg.default.weight == 2.0
    assert cfg.spec("acme").lane == "batch"
    assert cfg.spec("acme").api_keys == ("k1", "k2")
    with pytest.raises(ValueError, match="unknown keys"):
        parse_tenancy({"tenants": [{"name": "a", "wieght": 2}]})


def test_load_engine_config_reads_tenancy(tmp_path):
    (tmp_path / "model_config.json").write_text(json.dumps({
        "continuous_batching": {"slots": 4},
        "tenancy": {"tenants": [
            {"name": "acme", "weight": 3, "lane": "batch"}]},
    }))
    cfg = load_engine_config(str(tmp_path))
    assert cfg.slots == 4
    assert cfg.tenancy is not None
    assert cfg.tenancy.spec("acme").weight == 3.0
    assert load_engine_config("/nonexistent").tenancy is None


# -- weighted fair queueing (scheduler unit, no engine) ----------------------


def _req(tenant, lane="interactive", prompt=8, max_new=4):
    r = GenRequest(list(range(1, prompt + 1)), max_new_tokens=max_new,
                   temperature=0.0, top_k=0, top_p=1.0, seed=0,
                   tenant=tenant, lane=lane)
    return r


def _sched(cfg, slots=8, pages=0):
    return TenantScheduler(cfg, slots=slots, page_capacity=pages,
                           model="t")


def test_wfq_serves_in_weight_ratio():
    cfg = TenancyConfig(tenants=(TenantSpec("small", weight=1.0),
                                 TenantSpec("big", weight=3.0)))
    s = _sched(cfg, slots=100)  # quotas never bind in this unit
    for _ in range(40):
        s.append(_req("small"))
        s.append(_req("big"))
    served = {"small": 0, "big": 0}
    for _ in range(40):
        req = s.pop_next()
        served[req.tenant] += 1
        # identical service per request: 10 tokens' worth
        s.charge_prefill(req, 10)
        s.note_finished(req)
    # weight 3 tenant gets ~3x the service of weight 1
    assert served["big"] == pytest.approx(30, abs=2)
    assert served["small"] == pytest.approx(10, abs=2)


def test_wfq_quota_caps_under_contention_but_work_conserves():
    cfg = TenancyConfig(tenants=(TenantSpec("a"), TenantSpec("b")))
    s = _sched(cfg, slots=8)  # equal weights -> quota 4 each
    for _ in range(8):
        s.append(_req("a"))
    s.append(_req("b"))
    # drive a's vt to zero (min) so ONLY the quota can stop it
    popped = [s.pop_next() for _ in range(5)]
    # first four pops are a's (under quota, vt 0); the fifth must be
    # b's: a is AT quota while another tenant has queued work
    assert [r.tenant for r in popped] == ["a"] * 4 + ["b"]
    # b's queue is now empty -> nobody else wants the slot -> work
    # conservation hands a the capacity beyond its share
    assert s.pop_next().tenant == "a"


def test_wfq_page_quota_binds_in_paged_mode():
    cfg = TenancyConfig(tenants=(TenantSpec("a"), TenantSpec("b")))
    s = _sched(cfg, slots=16, pages=10)  # page quota 5 each
    s.append(_req("a"))
    s.append(_req("b"))
    s.note_pages("a", 5)  # a at its page quota
    assert s.pop_next().tenant == "b"


def test_vt_lift_denies_banked_credit():
    cfg = TenancyConfig(tenants=(TenantSpec("old"), TenantSpec("new")))
    s = _sched(cfg, slots=100)
    # "old" worked alone for a while
    for _ in range(3):
        s.append(_req("old"))
        req = s.pop_next()
        s.charge_prefill(req, 100)
        s.note_finished(req)
    # engine fully idle now; "new" (clock 0) arrives: it re-enters at
    # the floor, not at 0 — sitting out earns nothing
    s.append(_req("new"))
    assert s.state("new").vt >= s.state("old").vt - 1e-9


def test_lanes_drain_interactive_first_within_tenant():
    cfg = TenancyConfig(tenants=(TenantSpec("t"),))
    s = _sched(cfg)
    s.append(_req("t", lane="batch"))
    s.append(_req("t", lane="interactive"))
    assert s.pop_next().lane == "interactive"
    assert s.pop_next().lane == "batch"


def test_append_head_requeues_in_front():
    s = _sched(TenancyConfig())
    first, second = _req(DEFAULT_TENANT), _req(DEFAULT_TENANT)
    s.append(first)
    s.append(second)
    got = s.pop_next()
    assert got is first
    s.unpop(got)  # transient failure: back at the head
    assert s.pop_next() is first


def test_pick_victim_progress_guard_and_lane():
    cfg = TenancyConfig(tenants=(TenantSpec("g", lane="batch"),),
                        min_batch_progress=4)
    s = _sched(cfg)
    fresh = _req("g", lane="batch")
    fresh.tokens = [1, 2]          # below the guard
    old = _req("g", lane="batch")
    old.tokens = [1, 2, 3, 4, 5]   # past it
    inter = _req("g", lane="interactive")
    inter.tokens = [1] * 50        # wrong lane: never a victim
    assert s.pick_victim([(0, fresh), (1, old), (2, inter)]) == 1
    assert s.pick_victim([(0, fresh), (2, inter)]) is None


def test_pick_victim_tokenless_gated_on_free_resume():
    """A mid-chunked-prefill slot (no tokens yet) is exempt from the
    progress guard only when eviction is free (paged mode:
    ``tokenless_eligible=True``).  A dense engine re-chunks a victim
    from position 0, so there the exemption would let a sustained
    interactive stream starve a long prompt forever — tokenless slots
    must fall under the guard like everyone else."""
    cfg = TenancyConfig(tenants=(TenantSpec("g", lane="batch"),),
                        min_batch_progress=4)
    s = _sched(cfg)
    mid_prefill = _req("g", lane="batch")
    mid_prefill.tokens = []        # still chunking its prompt
    assert s.pick_victim([(0, mid_prefill)],
                         tokenless_eligible=True) == 0
    assert s.pick_victim([(0, mid_prefill)],
                         tokenless_eligible=False) is None
    # the default keeps the paged behavior the chunked-prefill
    # preemption tests lock
    assert s.pick_victim([(0, mid_prefill)]) == 0


def test_purge_and_drain_reach_every_tenant_queue():
    cfg = TenancyConfig(tenants=(TenantSpec("a"), TenantSpec("b")))
    s = _sched(cfg)
    reqs = [_req("a"), _req("b"), _req("b", lane="batch")]
    for r in reqs:
        s.append(r)
    reqs[1].cancelled = True
    dead = s.purge(lambda r: r.cancelled)
    assert dead == [reqs[1]]
    assert s.depth() == 2
    assert sorted(s.depths().items()) == [
        ("a", 1), ("b", 1), (DEFAULT_TENANT, 0)]
    assert set(s.drain()) == {reqs[0], reqs[2]}
    assert s.depth() == 0


# -- trace schema + generators (the --trace quick-lane satellite) ------------


def test_trace_fixture_validates():
    entries = trace_mod.load_trace(FIXTURE)
    assert len(entries) > 50
    tenants = {e["tenant"] for e in entries}
    assert len(tenants) >= 2  # Zipf mix, several tenants
    lanes = {e.get("lane") for e in entries}
    assert "interactive" in lanes and "batch" in lanes


@pytest.mark.parametrize("bad, msg", [
    ({"tenant": "a", "prompt_tokens": 3}, "missing 't'"),
    ({"t": -1.0, "prompt_tokens": 3}, "t must be"),
    ({"t": 0.0}, "exactly one of"),
    ({"t": 0.0, "prompt": "x", "prompt_tokens": 3}, "exactly one of"),
    ({"t": 0.0, "prompt_tokens": 0}, "prompt_tokens"),
    ({"t": 0.0, "prompt": "x", "lane": "bulk"}, "lane"),
    ({"t": 0.0, "prompt": "x", "nope": 1}, "unknown fields"),
    ({"t": 0.0, "prompt": "x", "max_new_tokens": True},
     "max_new_tokens"),
])
def test_trace_schema_rejections(bad, msg):
    with pytest.raises(ValueError, match=msg):
        trace_mod.validate_trace([bad])


def test_trace_generators_deterministic_and_distinct():
    kw = dict(duration_s=10.0, rate_rps=5.0, n_tenants=3, seed=3)
    for kind in ("poisson", "bursty", "diurnal"):
        a = trace_mod.generate_trace(kind=kind, **kw)
        b = trace_mod.generate_trace(kind=kind, **kw)
        assert a == b  # same seed = byte-identical
        trace_mod.validate_trace(a)
        assert a != trace_mod.generate_trace(kind=kind, duration_s=10.0,
                                             rate_rps=5.0, n_tenants=3,
                                             seed=4)


def test_trace_zipf_head_dominates():
    w = trace_mod.zipf_weights(4, 1.2)
    assert w[0] > w[1] > w[2] > w[3]
    assert sum(w) == pytest.approx(1.0)


def test_jain_index():
    assert trace_mod.jain_index([5, 5, 5, 5]) == 1.0
    assert trace_mod.jain_index([1, 0, 0, 0]) == 0.25
    assert trace_mod.jain_index([]) is None
    assert trace_mod.jain_index([0, 0]) is None


def test_trace_save_load_roundtrip(tmp_path):
    entries = trace_mod.generate_trace(duration_s=3.0, rate_rps=5.0,
                                       seed=1)
    path = str(tmp_path / "t.jsonl")
    trace_mod.save_trace(path, entries)
    assert trace_mod.load_trace(path) == entries


def test_entry_payload_identity_channels():
    body, headers = trace_mod.entry_payload(
        {"t": 0.0, "tenant": "acme", "api_key": "k1",
         "prompt_tokens": 5, "id": "r-1"})
    assert headers["X-API-Key"] == "k1"  # header wins when present
    payload = json.loads(body)
    assert len(payload["instances"][0]) == 5  # byte tokenizer 1:1
    body2, headers2 = trace_mod.entry_payload(
        {"t": 0.0, "tenant": "acme", "prompt": "hi", "lane": "batch"})
    assert "X-API-Key" not in headers2
    p2 = json.loads(body2)
    assert p2["tenant"] == "acme" and p2["lane"] == "batch"


# -- engine integration (slot mode; paged + preemption in chaos file) --------


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


TEN = TenancyConfig(tenants=(
    TenantSpec("acme", weight=2.0, lane="batch", api_keys=("k-acme",)),
    TenantSpec("beta", weight=1.0, api_keys=("k-beta",)),
))

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]


@pytest.fixture(scope="module")
def reference(params):
    refs = []
    for p, n in zip(PROMPTS, MAX_NEW):
        out = np.asarray(generate(CFG, params, jnp.asarray([p], jnp.int32),
                                  max_new_tokens=n, temperature=0.0,
                                  pad_token_id=0))
        refs.append(out[0, len(p):len(p) + n].tolist())
    return refs


def make_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("tenancy", TEN)
    eng = ContinuousBatchingEngine(CFG, params, EngineConfig(**kw),
                                   eos_token_id=None, pad_token_id=0)
    eng.start()
    return eng


def test_token_identity_with_tenants_mixed_admission(params, reference):
    """WFQ admission order must never change any request's tokens."""
    eng = make_engine(params)
    try:
        keys = ["k-acme", "k-beta", None, "k-acme"]
        reqs = [eng.submit(PROMPTS[i], max_new_tokens=MAX_NEW[i],
                           temperature=0.0, api_key=keys[i])
                for i in range(4)]
        for i, r in enumerate(reqs):
            assert r.wait(eng) == reference[i]
        assert reqs[0].tenant == "acme" and reqs[0].lane == "batch"
        assert reqs[1].tenant == "beta"
        assert reqs[2].tenant == DEFAULT_TENANT
    finally:
        eng.stop()
    stats = eng.tenants.stats()
    assert stats["acme"]["admitted"] == 2
    assert stats["beta"]["admitted"] == 1
    assert stats[DEFAULT_TENANT]["admitted"] == 1
    assert stats["acme"]["decode_tokens"] == MAX_NEW[0] + MAX_NEW[3]


def test_quota_shed_is_typed_with_retry_hint(params):
    ten = TenancyConfig(tenants=(
        TenantSpec("limited", req_rate=1.0, req_burst=2.0,
                   api_keys=("k-lim",)),))
    eng = make_engine(params, tenancy=ten)
    try:
        for _ in range(2):  # burst passes
            eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                       api_key="k-lim")
        with pytest.raises(TenantQuotaError) as ei:
            eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                       api_key="k-lim")
        assert ei.value.retry_after_s > 0.0
        # the shed never touched the shared queue: other tenants fine
        ok = eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0)
        assert len(ok.wait(eng)) == 2
        assert eng.tenants.stats()["limited"]["shed"] == 1
    finally:
        eng.stop()


def test_token_quota_counts_prompt_tokens(params):
    ten = TenancyConfig(tenants=(
        TenantSpec("tok", token_rate=1.0, token_burst=25.0,
                   api_keys=("k-tok",)),))
    eng = make_engine(params, tenancy=ten)
    try:
        eng.submit(list(range(1, 21)), max_new_tokens=2,
                   temperature=0.0, api_key="k-tok")  # 20 of 25
        with pytest.raises(TenantQuotaError, match="prompt-token"):
            eng.submit(list(range(1, 21)), max_new_tokens=2,
                       temperature=0.0, api_key="k-tok")
        # a prompt that can NEVER fit the burst is a 400 config error,
        # not a retryable 503 (the hint would hot-loop the client)
        with pytest.raises(ValueError, match="token-bucket burst"):
            eng.submit(list(range(1, 31)), max_new_tokens=2,
                       temperature=0.0, api_key="k-tok")
    finally:
        eng.stop()


def test_shed_refunds_bucket_charge(params):
    """A queue-full (or deadline) shed must give the bucket charge
    back: the tenant got no service, so sustained backpressure cannot
    lock it out below its contracted rate."""
    ten = TenancyConfig(tenants=(
        TenantSpec("lim", req_rate=1.0, req_burst=1.0,
                   api_keys=("k-lim",)),))
    eng = make_engine(params, slots=1, max_queue_size=1, tenancy=ten)
    try:
        hold = eng.submit(PROMPTS[2], max_new_tokens=40,
                          temperature=0.0)  # default tenant: no bucket
        next(hold.iter_tokens(timeout=60))
        filler = eng.submit(PROMPTS[3], max_new_tokens=2,
                            temperature=0.0)  # queue now full
        from kubernetes_cloud_tpu.serve.errors import QueueFullError

        with pytest.raises(QueueFullError):
            eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                       api_key="k-lim")
        filler.wait(eng)  # queue drains
        # the shed refunded lim's single-token burst: this submission
        # must pass the bucket again instead of 503ing on quota
        ok = eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                        api_key="k-lim")
        assert len(ok.wait(eng)) == 2
        hold.wait(eng)
    finally:
        eng.stop()


def test_queue_depth_aggregates_across_tenant_queues(params):
    """Satellite: estimated_queue_delay / readiness must see EVERY
    tenant queue, not one global deque."""
    eng = make_engine(params, slots=1)
    try:
        hold = eng.submit(PROMPTS[2], max_new_tokens=40,
                          temperature=0.0, api_key="k-acme")
        next(hold.iter_tokens(timeout=60))  # occupies the only slot
        queued = [eng.submit(PROMPTS[3], max_new_tokens=2,
                             temperature=0.0, api_key=k)
                  for k in ("k-acme", "k-beta", None)]
        assert eng.queue_depth() == 3
        depths = eng.tenants.depths()
        assert depths["acme"] == 1 and depths["beta"] == 1
        assert depths[DEFAULT_TENANT] == 1
        eng.iter_s = 1.0  # force a nonzero per-iteration estimate
        assert eng.estimated_queue_delay() > 0.0
        for q in queued:
            q.wait(eng)
        hold.wait(eng)
    finally:
        eng.stop()


def test_deadline_queued_shed_refunds_bucket(params):
    """Expiring IN the queue refunds the admission charge exactly like
    the at-the-door sheds — zero service must cost zero quota."""
    import time as _time

    ten = TenancyConfig(tenants=(
        TenantSpec("lim", req_rate=0.01, req_burst=1.0,
                   api_keys=("k-lim",)),))
    eng = make_engine(params, slots=1, tenancy=ten)
    try:
        hold = eng.submit(PROMPTS[2], max_new_tokens=40,
                          temperature=0.0)
        next(hold.iter_tokens(timeout=60))  # slot busy
        doomed = eng.submit(PROMPTS[3], max_new_tokens=2,
                            temperature=0.0, api_key="k-lim",
                            deadline=_time.monotonic() + 0.02)
        from kubernetes_cloud_tpu.serve.errors import (
            DeadlineExceededError,
        )

        with pytest.raises(DeadlineExceededError):
            doomed.wait(eng)  # expires while queued -> shed + refund
        hold.wait(eng)
        # the refund restored lim's one-token burst (rate ~0 would
        # never refill it): this submission passes the bucket again
        ok = eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                        api_key="k-lim")
        assert len(ok.wait(eng)) == 2
    finally:
        eng.stop()


def test_queue_bound_is_per_tenant_share(params):
    """One tenant's flood fills only its own slice of the bounded
    queue: neighbours keep admitting (the isolation contract), and
    the aggregate bound still backstops total memory."""
    ten = TenancyConfig(tenants=(TenantSpec("a", api_keys=("k-a",)),
                                 TenantSpec("b", api_keys=("k-b",))))
    # 3 equal weights (a, b, default) over bound 6 -> share 2 each
    eng = make_engine(params, slots=1, max_queue_size=6, tenancy=ten)
    try:
        hold = eng.submit(PROMPTS[2], max_new_tokens=40,
                          temperature=0.0, api_key="k-a")
        next(hold.iter_tokens(timeout=60))  # occupies the only slot
        from kubernetes_cloud_tpu.serve.errors import QueueFullError

        flood = [eng.submit(PROMPTS[3], max_new_tokens=2,
                            temperature=0.0, api_key="k-a")
                 for _ in range(2)]  # a's share of the queue
        with pytest.raises(QueueFullError):
            eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                       api_key="k-a")
        # the neighbour's slice is untouched by a's flood
        ok = eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                        api_key="k-b")
        assert len(ok.wait(eng)) == 2
        for r in flood:
            r.wait(eng)
        hold.wait(eng)
    finally:
        eng.stop()


def test_deadline_estimate_is_tenant_aware(params):
    """A batch tenant's deep backlog must not shed another tenant's
    deadline-bearing request at the door — the WFQ-aware estimate
    looks at the submitting tenant's OWN queue."""
    eng = make_engine(params, slots=1, max_queue_size=64)
    try:
        hold = eng.submit(PROMPTS[2], max_new_tokens=40,
                          temperature=0.0, api_key="k-acme")
        next(hold.iter_tokens(timeout=60))
        for _ in range(10):  # acme's backlog
            eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                       api_key="k-acme")
        eng.iter_s = 1.0  # aggregate FIFO estimate would be ~5s
        assert eng.estimated_queue_delay() > 2.0
        # beta's own queue is empty: its estimate is ~0, so a tight
        # deadline is admitted instead of shed at the door
        assert eng.estimated_queue_delay("beta") == 0.0
        req = eng.submit(PROMPTS[3], max_new_tokens=2, temperature=0.0,
                         api_key="k-beta",
                         deadline=__import__("time").monotonic() + 2.0)
        assert req.tenant == "beta"
    finally:
        eng.stop()


def test_debug_tenants_snapshot(params):
    eng = make_engine(params)
    try:
        req = eng.submit(PROMPTS[0], max_new_tokens=4, temperature=0.0,
                         api_key="k-acme")
        req.wait(eng)
        snap = eng.debug_tenants()
        assert snap["acme"]["lane"] == "batch"
        assert snap["acme"]["weight"] == 2.0
        assert snap["acme"]["decode_tokens"] == 4
        assert "slot_quota" in snap["acme"]
        slots = eng.debug_slots()
        assert all("tenant" in s or s["state"] == "free" for s in slots)
    finally:
        eng.stop()
