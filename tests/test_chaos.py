"""Preemption chaos tests — kill a training process mid-run (SIGKILL and
graceful SIGTERM), resume in a fresh process, verify completion.

SURVEY.md §5.3: the reference has no preemption handling beyond Argo
step retries and a launcher-restart hack
(``gpt-neox/04-finetune-workflow.yaml:420-425``); GKE TPU slices are
preemptible, so kill-resume is a first-class test here.  Workers run in
subprocesses on the CPU-simulated mesh.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one worker template serves both the hard-kill and graceful scenarios
WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.data.tokenized import TokenizedDataset
from kubernetes_cloud_tpu.models.causal_lm import PRESETS
from kubernetes_cloud_tpu.train.train_step import TrainConfig
from kubernetes_cloud_tpu.train.trainer import Trainer, TrainerConfig
import jax

class SlowDataset(TokenizedDataset):
    # throttles the input pipeline so the signal lands mid-run
    def gather(self, rows):
        time.sleep({slow!r})
        return super().gather(rows)

mesh = build_mesh(MeshSpec(data=2), devices=jax.devices("cpu")[:2])
ds = SlowDataset({data!r}, context_size=32)
trainer = Trainer(
    PRESETS["test-tiny"], TrainConfig(warmup_steps=2, total_steps=24),
    TrainerConfig(run_name={run_name!r}, output_path={out!r}, batch_size=4,
                  gradients=2, epochs=3, save_steps={save_steps},
                  logs={logs!r}, prompt_every=0),
    mesh, ds)
if {graceful!r}:
    trainer.install_preemption_handler()
    print("READY", flush=True)
result = trainer.train()
print("RESULT", result.get("preempted"), result["steps"], flush=True)
"""


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _write_worker(tmp_path, slow, *, name, run_name, save_steps, graceful):
    data = str(tmp_path / "data.tokens")
    if not os.path.exists(data):
        np.random.RandomState(0).randint(
            2, 500, size=(64, 32)).astype(np.uint16).tofile(data)
    script = tmp_path / name
    script.write_text(WORKER.format(
        repo=REPO, data=data, out=str(tmp_path),
        logs=str(tmp_path / "logs"), slow=slow,
        run_name=run_name, save_steps=save_steps, graceful=graceful))
    return str(script)


def test_kill_and_resume(tmp_path):
    run_dir = tmp_path / "results-chaos"
    script = _write_worker(tmp_path, 0.5, name="w1.py", run_name="chaos",
                           save_steps=2, graceful=False)

    # phase 1: start training, SIGKILL once the first checkpoint lands
    p = subprocess.Popen([sys.executable, script], env=_env(),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 300
    killed_at = None
    try:
        while time.monotonic() < deadline:
            ckpts = [d for d in (os.listdir(run_dir)
                                 if run_dir.exists() else [])
                     if d.startswith("checkpoint")]
            if ckpts:
                p.send_signal(signal.SIGKILL)
                killed_at = ckpts
                break
            if p.poll() is not None:
                out = p.stdout.read().decode()
                raise AssertionError(
                    f"worker exited before checkpointing:\n{out}")
            time.sleep(0.3)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    assert killed_at, "no checkpoint appeared within the deadline"
    # a hard kill must not have produced the final artifact
    assert not (run_dir / ".ready.txt").exists()

    # phase 2: fresh process resumes and completes
    script2 = _write_worker(tmp_path, 0.0, name="w2.py", run_name="chaos",
                            save_steps=2, graceful=False)
    out = subprocess.run([sys.executable, script2], env=_env(),
                         capture_output=True, text=True, timeout=600)
    assert "RESULT None 24" in out.stdout, out.stdout + out.stderr
    assert (run_dir / ".ready.txt").exists()
    assert (run_dir / "final" / "model.tensors").exists()

    # the resumed run started from the checkpoint, not step 0: its metrics
    # stream must reach exactly the final step
    logs = list((tmp_path / "logs").glob("*.jsonl"))
    assert logs
    steps_logged = []
    for lf in logs:
        for line in open(lf):
            rec = json.loads(line)
            if "step" in rec:
                steps_logged.append(rec["step"])
    assert max(steps_logged) == 24


def test_sigterm_graceful_checkpoint(tmp_path):
    """SIGTERM mid-run: the trainer checkpoints at the step boundary and
    exits cleanly; a resume completes from there (GKE preemption path —
    save_steps=100 means the ONLY checkpoint comes from the handler)."""
    script = _write_worker(tmp_path, 0.4, name="w.py", run_name="term",
                           save_steps=100, graceful=True)
    run_dir = tmp_path / "results-term"

    p = subprocess.Popen([sys.executable, script], env=_env(),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
    try:
        # wait until the handler is installed and a few throttled steps
        # ran, then deliver SIGTERM.  A pump thread owns the buffered
        # stream (selectors on the raw fd would race Python's buffer).
        import queue as queue_mod
        import threading

        lines: "queue_mod.Queue[str]" = queue_mod.Queue()
        captured: list[str] = []

        def pump():
            for line in p.stdout:
                captured.append(line)
                lines.put(line)

        pump_thread = threading.Thread(target=pump, daemon=True)
        pump_thread.start()
        deadline = time.monotonic() + 300
        ready = False
        while time.monotonic() < deadline and not ready:
            try:
                ready = "READY" in lines.get(timeout=1.0)
            except queue_mod.Empty:
                if p.poll() is not None:
                    break  # worker died before READY; fail fast below
        assert ready, "worker never reached READY"
        time.sleep(6)  # a few throttled steps
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=300)
        pump_thread.join(timeout=30)
        out = "".join(captured)
    finally:
        if p.poll() is None:
            p.kill()
    assert "RESULT True" in out, out
    ckpts = [d for d in os.listdir(run_dir) if d.startswith("checkpoint")]
    assert ckpts, out  # handler saved despite save_steps=100
    assert not (run_dir / ".ready.txt").exists()  # run was NOT complete

    # resume: same config minus throttle completes to 24
    script2 = _write_worker(tmp_path, 0.0, name="w2.py", run_name="term",
                            save_steps=100, graceful=True)
    out2 = subprocess.run([sys.executable, script2], env=_env(),
                          capture_output=True, text=True, timeout=600)
    assert "RESULT None 24" in out2.stdout, out2.stdout + out2.stderr
    assert (run_dir / ".ready.txt").exists()
