import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubernetes_cloud_tpu.core import (
    BATCH_AXES,
    MeshSpec,
    build_mesh,
    local_batch_size,
)
from kubernetes_cloud_tpu.utils.compat import shard_map


def test_default_spec_fills_data_axis(devices8):
    mesh = build_mesh(MeshSpec(), devices=devices8)
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1


def test_fsdp_tp_mesh(devices8):
    mesh = build_mesh(MeshSpec(data=1, fsdp=4, model=2), devices=devices8)
    assert mesh.shape["fsdp"] == 4
    assert mesh.shape["model"] == 2
    assert mesh.axis_names == ("data", "fsdp", "stage", "expert", "seq",
                               "model")


def test_bad_spec_raises(devices8):
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(data=3, model=2), devices=devices8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, fsdp=-1).ici_shape(8)


def test_sharded_computation_runs(devices8):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2), devices=devices8)
    x = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
        NamedSharding(mesh, P(BATCH_AXES, None)),
    )
    y = jax.jit(lambda a: a @ a.T)(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ np.asarray(x).T)


def test_psum_over_mesh(devices8):
    mesh = build_mesh(MeshSpec(data=8), devices=devices8)
    x = jax.device_put(
        jnp.ones((8, 4)), NamedSharding(mesh, P("data", None))
    )
    out = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a, "data"),
            mesh=mesh, in_specs=P("data", None), out_specs=P(None, None),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((1, 4), 8.0))


def test_local_batch_size(devices8):
    mesh = build_mesh(MeshSpec(data=4, fsdp=2), devices=devices8)
    assert local_batch_size(32, mesh) == 32  # single process owns all shards
    with pytest.raises(ValueError):
        local_batch_size(12, mesh)


def test_hybrid_dcn_mesh(devices8):
    """Multi-slice spec: outer DCN axes merge into the matching logical
    axis (2 slices x 4-device ICI mesh -> one 8-device mesh), and every
    device appears exactly once."""
    mesh = build_mesh(MeshSpec(data=1, fsdp=2, model=2, dcn_data=2),
                      devices=devices8)
    assert dict(mesh.shape)["data"] == 2
    assert dict(mesh.shape)["fsdp"] == 2
    assert dict(mesh.shape)["model"] == 2
    assert {d.id for d in mesh.devices.flat} == {d.id for d in devices8}

    spec = MeshSpec(data=1, fsdp=2, model=2, dcn_data=2)
    assert spec.is_multislice
    with pytest.raises(ValueError):
        # 8 devices don't divide into 3 slices
        build_mesh(MeshSpec(data=1, dcn_data=3), devices=devices8)


def test_hybrid_fallback_is_silent_only_for_cpu_sim(devices8):
    """The topology-unaware hybrid-mesh fallback is legitimate for CPU
    simulation devices (no ``slice_index``) and must stay silent
    there; on devices that DO report ``slice_index`` (real multi-slice
    TPU) it must warn loudly — silently misplacing DCN/ICI axes is a
    perf cliff nobody would see (ADVICE.md mesh.py:144)."""
    import warnings

    # CPU sim: fallback may trigger, never warns
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        build_mesh(MeshSpec(data=1, fsdp=2, model=2, dcn_data=2),
                   devices=devices8)

    class _SliceyDevice:
        """Real-TPU-shaped device: reports slice_index (all in slice
        0, so a 2-slice hybrid build fails and takes the fallback)."""

        def __init__(self, dev):
            self._dev = dev
            self.slice_index = 0

        def __getattr__(self, name):
            return getattr(self._dev, name)

    proxies = [_SliceyDevice(d) for d in devices8]
    with pytest.warns(RuntimeWarning, match="slice_index"):
        try:
            build_mesh(MeshSpec(data=1, fsdp=2, model=2, dcn_data=2),
                       devices=proxies)
        except Exception:  # noqa: BLE001 - proxy devices need not
            pass           # survive Mesh(); the loud warning is the lock
