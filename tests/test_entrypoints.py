"""Every container command in deploy/ must resolve to a real module with
a ``main``; plus functional smoke tests for the new entrypoints."""

import importlib
import json
import os
import re

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY = os.path.join(REPO, "deploy")


def manifest_commands() -> set[str]:
    mods = set()
    pat = re.compile(r'"-m",\s*"(kubernetes_cloud_tpu\.[\w.]+)"')
    for root, _, files in os.walk(DEPLOY):
        for fn in files:
            if fn.endswith((".yaml", ".yml")):
                mods.update(pat.findall(open(os.path.join(root, fn)).read()))
    return mods


def test_all_manifest_commands_resolve():
    mods = manifest_commands()
    assert mods, "no commands found under deploy/"
    missing = []
    for mod in sorted(mods):
        try:
            m = importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001
            missing.append(f"{mod}: import failed: {e}")
            continue
        if not hasattr(m, "main"):
            missing.append(f"{mod}: no main()")
    assert not missing, "\n".join(missing)


# -------------------------------------------------------------------------
# functional smokes


def test_downloader_entrypoints(tmp_path):
    from kubernetes_cloud_tpu.data import dataset_downloader, downloader

    src = tmp_path / "snap"
    src.mkdir()
    (src / "config.json").write_text("{}")
    (src / "tokenizer.json").write_text("{}")
    (src / "model.safetensors").write_bytes(b"\0" * 4)
    assert downloader.main(["--model", str(src),
                            "--dest", str(tmp_path / "m")]) == 0
    assert (tmp_path / "m" / ".ready.txt").exists()

    corpus = tmp_path / "c.txt"
    corpus.write_text("text")
    assert dataset_downloader.main(
        ["--output", str(tmp_path / "d"), "--urls", corpus.as_uri()]) == 0
    assert (tmp_path / "d" / "c.txt").exists()


def test_sd_serialize_entrypoint(tmp_path, devices8):
    from tests.test_diffusion import (
        TINY_CLIP,
        TINY_UNET,
        TINY_VAE,
        _write_images,
    )
    from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
    from kubernetes_cloud_tpu.data.diffusion import LocalBase, collate_images
    from kubernetes_cloud_tpu.train.sd_trainer import (
        SDTrainerConfig,
        StableDiffusionTrainer,
    )
    from kubernetes_cloud_tpu.weights import sd_serialize

    root = _write_images(tmp_path)
    mesh = build_mesh(MeshSpec(data=2), devices=devices8[:2])
    trainer = StableDiffusionTrainer(
        SDTrainerConfig(run_name="ser", output_path=str(tmp_path),
                        batch_size=2, lr=1e-4, epochs=1, save_steps=0,
                        image_log_steps=0, resolution=32, use_ema=False,
                        logs=str(tmp_path / "logs")),
        mesh, LocalBase(root, size=32, ucg=0.0, seed=0), collate_images,
        unet_cfg=TINY_UNET, vae_cfg=TINY_VAE, clip_cfg=TINY_CLIP)
    trainer.train()

    dest = tmp_path / "serving"
    rc = sd_serialize.main(["--model",
                            str(tmp_path / "results-ser"),
                            "--dest", str(dest)])
    assert rc == 0
    for mod in ("encoder", "vae", "unet"):
        assert (dest / f"{mod}.tensors").exists()
    assert (dest / ".ready.txt").exists()


def test_classifier_service_roundtrip(tmp_path, devices8):
    import dataclasses

    from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
    from kubernetes_cloud_tpu.data.images import synthetic_batches
    from kubernetes_cloud_tpu.models.vision.resnet import PRESETS
    from kubernetes_cloud_tpu.serve.classifier_service import (
        VisionClassifierService,
    )
    from kubernetes_cloud_tpu.train.vision_trainer import (
        VisionTrainConfig,
        init_vision_state,
        make_vision_train_step,
        save_classifier,
        train_epoch,
    )

    cfg = PRESETS["resnet-tiny"]
    mesh = build_mesh(MeshSpec(data=2), devices=devices8[:2])
    tcfg = VisionTrainConfig(learning_rate=0.01, steps_per_epoch=4)
    state = init_vision_state(cfg, tcfg, jax.random.key(0), mesh)
    step = jax.jit(make_vision_train_step(cfg, tcfg), donate_argnums=0)
    state, _ = train_epoch(
        step, state,
        synthetic_batches(8, image_size=32, num_classes=cfg.num_classes,
                          steps=4),
        mesh=mesh)
    final = save_classifier(str(tmp_path / "final"), cfg, state)

    svc = VisionClassifierService("classifier", final)
    svc.load()
    assert svc.ready
    imgs = np.zeros((2, 32, 32, 3), np.float32)
    out = svc.predict({"instances": imgs.tolist()})
    assert len(out["predictions"]) == 2
    assert len(out["predictions"][0]) == cfg.num_classes
    with pytest.raises(ValueError):
        svc.predict({"instances": [[1, 2, 3]]})


def test_sd_finetuner_cli_end_to_end(tmp_path, devices8):
    """CLI resumes from a published module split (the downloader/
    serializer layout) and finetunes it — the workflow's trainer step."""
    from tests.test_diffusion import (
        TINY_CLIP,
        TINY_UNET,
        TINY_VAE,
        _write_images,
    )
    from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
    from kubernetes_cloud_tpu.data.diffusion import LocalBase, collate_images
    from kubernetes_cloud_tpu.train import sd_finetuner_cli
    from kubernetes_cloud_tpu.train.sd_trainer import (
        SDTrainerConfig,
        StableDiffusionTrainer,
    )

    root = _write_images(tmp_path)
    # publish a tiny pretrained module split
    mesh = build_mesh(MeshSpec(data=2), devices=devices8[:2])
    pre = StableDiffusionTrainer(
        SDTrainerConfig(run_name="pre", output_path=str(tmp_path),
                        batch_size=2, lr=1e-4, epochs=1, save_steps=0,
                        image_log_steps=0, resolution=32, use_ema=False,
                        logs=str(tmp_path / "logs")),
        mesh, LocalBase(root, size=32, ucg=0.0, seed=0), collate_images,
        unet_cfg=TINY_UNET, vae_cfg=TINY_VAE, clip_cfg=TINY_CLIP)
    pre.train()

    rc = sd_finetuner_cli.main([
        "--run_name", "sdcli",
        "--model", str(tmp_path / "results-pre" / "final"),
        "--dataset", root, "--resolution", "32", "--batch_size", "2",
        "--epochs", "1", "--save_steps", "0", "--image_log_steps", "0",
        "--use_ema", "false", "--lr", "1e-4", "--use_8bit_adam", "true",
        "--gradient_checkpointing", "true", "--lr_scheduler", "cosine",
        "--output_path", str(tmp_path),
    ])
    assert rc == 0
    run = tmp_path / "results-sdcli"
    assert (run / "final" / "unet.tensors").exists()
    assert (run / "final" / ".ready.txt").exists()


def test_lm_service_main_builds_and_serves(tmp_path, devices8):
    """--model dir with trainer-final layout boots the full service."""
    import urllib.request

    from kubernetes_cloud_tpu.models.causal_lm import (
        PRESETS,
        init_params,
    )
    from kubernetes_cloud_tpu.serve import boot, lm_service
    from kubernetes_cloud_tpu.weights.tensorstream import write_pytree
    import dataclasses

    cfg = PRESETS["test-tiny"]
    params = init_params(cfg, jax.random.key(0))
    final = tmp_path / "final"
    final.mkdir()
    meta_cfg = dataclasses.asdict(dataclasses.replace(
        cfg, dtype=str(cfg.dtype), param_dtype=str(cfg.param_dtype)))
    write_pytree(str(final / "model.tensors"), jax.device_get(params),
                 meta={"model_config": meta_cfg})

    # build the service exactly as main() does, then serve via boot
    weights = lm_service._resolve_weights(str(final))
    loaded_cfg = lm_service._config_from_artifact(weights, None)
    assert loaded_cfg.vocab_size == cfg.vocab_size
    svc = lm_service.CausalLMService(
        "m", dataclasses.replace(loaded_cfg), weights_path=weights)

    class A:  # minimal args namespace for boot
        model_name = "m"
        port = 0
        ready_file = None
        ready_timeout = 1.0
        frontend = "python"

    server = boot.make_server([svc], A)
    server.load_all()
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/models/m:predict",
            data=json.dumps({"instances": ["ab"],
                             "parameters": {"max_new_tokens": 4,
                                            "temperature": 0.0}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        assert "generated_text" in out["predictions"][0]
    finally:
        server.stop()


def test_compile_cache_flag(tmp_path):
    import argparse

    from kubernetes_cloud_tpu.serve import boot

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        ap = argparse.ArgumentParser()
        boot.add_common_args(ap)
        args = ap.parse_args(["--compile-cache", str(tmp_path / "cache")])
        boot.enable_compile_cache(args)  # must not raise
        assert jax.config.jax_compilation_cache_dir == str(
            tmp_path / "cache")
        args2 = ap.parse_args(["--compile-cache", ""])
        boot.enable_compile_cache(args2)  # disabled path
    finally:
        # global jax config must not leak into later tests
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
