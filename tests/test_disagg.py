"""Prefill/decode disaggregation: identity, zero re-prefill, chaos.

DistServe-style role split (``serve/disagg.py``): a prefill-role
engine admits + prefills, then hands each request's prompt KV over
page-granularly to a decode-role engine that resumes it through the
pinned-pages path.  The locks:

* greedy output through the disaggregated pair is token-identical to
  one-shot ``generate`` for any admission order (incl. prefix
  sharing on the prefill side);
* the happy-path handover re-prefills NOTHING — ``stats
  ["reprefill_tokens"] == 0`` while pages move (the acceptance
  counter);
* a decode-slice death transplants its queued requests onto a
  survivor, which re-prefills them token-identically (actives fail
  with the typed retryable 503 — the client-retry contract);
* the composition with the mesh: the pair over a 2-shard TP mesh is
  still token-identical (sharded extract → sharded install);
* the fleet router learns roles from probe bodies and keeps
  admission traffic off decode-role replicas.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.disagg import build_disaggregated_engine
from kubernetes_cloud_tpu.serve.errors import RetryableError
from kubernetes_cloud_tpu.serve.fleet import (
    FleetConfig,
    ReplicaHealth,
    _probe_healthy,
)

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def greedy_ref(params, prompt, n):
    out = np.asarray(generate(CFG, params,
                              jnp.asarray([prompt], jnp.int32),
                              max_new_tokens=n, temperature=0.0,
                              pad_token_id=0))
    return out[0, len(prompt):len(prompt) + n].tolist()


def make_pair(params, mesh=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    kw.setdefault("role", "prefill")
    kw.setdefault("decode_slices", 1)
    pair = build_disaggregated_engine(
        CFG, params, EngineConfig(**kw), eos_token_id=None,
        pad_token_id=0, mesh=mesh, name="pair")
    pair.start()
    return pair


# ---------------------------------------------------------------------------
# identity + zero re-prefill
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("order", [[0, 1, 2, 3], [3, 2, 1, 0]])
def test_disagg_token_identical_to_generate(params, order):
    refs = {i: greedy_ref(params, PROMPTS[i], MAX_NEW[i]) for i in order}
    pair = make_pair(params)
    try:
        reqs = {i: pair.submit(PROMPTS[i], max_new_tokens=MAX_NEW[i],
                               temperature=0.0) for i in order}
        got = {i: reqs[i].wait() for i in order}
    finally:
        pair.stop()
    assert got == refs
    st = pair.stats
    # page-granular handover, zero re-prefill on the happy path
    assert st["engines"]["pair-prefill"]["handoffs"] == len(order)
    assert st["adopted"] == len(order)
    assert st["reprefill_tokens"] == 0
    assert st["kv_transfer_pages"] > 0
    # the decode side computed no prefill at all
    decode_stats = st["engines"]["pair-decode0"]
    assert decode_stats["prefill_tokens"] == 0
    assert decode_stats["emitted_tokens"] > 0


def test_disagg_prefix_sharing_on_prefill_side(params):
    """The prefix cache lives where admission lives: sharing dedups
    prefill compute BEFORE the handover, and outputs stay identical."""
    shared = list(range(200, 224))
    prompts = [shared + [t] for t in (5, 6)]
    refs = [greedy_ref(params, p, 5) for p in prompts]
    pair = make_pair(params)
    try:
        for p, ref in zip(prompts, refs):
            assert pair.submit(p, max_new_tokens=5,
                               temperature=0.0).wait() == ref
        st = pair.stats["engines"]["pair-prefill"]
        assert st["prefix_hits"] == 1
        assert st["prefix_tokens_saved"] == 24
    finally:
        pair.stop()


def test_single_token_request_never_hands_off(params):
    """max_new_tokens=1 completes inside the prefill engine (its one
    token IS the prefill logits' sample) — no transfer, no decode."""
    ref = greedy_ref(params, PROMPTS[0], 1)
    pair = make_pair(params)
    try:
        assert pair.submit(PROMPTS[0], max_new_tokens=1,
                           temperature=0.0).wait() == ref
        st = pair.stats
        assert st["engines"]["pair-prefill"]["handoffs"] == 0
        assert st["adopted"] == 0
    finally:
        pair.stop()


def test_disagg_over_mesh_token_identical(params):
    """The full composition: disaggregated pair where every engine is
    a 2-shard TP mesh engine — sharded prefill, sharded extract,
    sharded install, sharded decode."""
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("need 2 cpu devices")
    mesh = build_mesh(MeshSpec(data=1, model=2), devices=devs[:2])
    refs = {i: greedy_ref(params, PROMPTS[i], MAX_NEW[i])
            for i in (0, 3)}
    pair = make_pair(params, mesh=mesh)
    assert pair.prefill._tp_active
    try:
        reqs = {i: pair.submit(PROMPTS[i], max_new_tokens=MAX_NEW[i],
                               temperature=0.0) for i in (0, 3)}
        got = {i: reqs[i].wait() for i in (0, 3)}
    finally:
        pair.stop()
    assert got == refs
    assert pair.stats["reprefill_tokens"] == 0


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_kv_transfer_metrics_and_phase(params):
    pair = make_pair(params)
    try:
        pair.submit(PROMPTS[1], max_new_tokens=6, temperature=0.0).wait()
        decode = pair.decodes[0]
        recs = decode.flight.tail(64)
        assert any("kv_transfer" in r["phases"] for r in recs)
        samples = obs.parse_text(obs.render_text())
        assert obs.sample_value(
            samples, "kct_engine_kv_transfer_pages_total",
            {"model": "pair-prefill", "direction": "out"}) > 0
        assert obs.sample_value(
            samples, "kct_engine_kv_transfer_pages_total",
            {"model": "pair-decode0", "direction": "in"}) > 0
        assert obs.sample_value(
            samples, "kct_engine_kv_transfer_seconds_count",
            {"model": "pair-decode0"}) >= 1
        # role-labeled iteration histogram: both sides visible
        assert obs.sample_value(
            samples, "kct_engine_iteration_seconds_count",
            {"model": "pair-prefill", "role": "prefill"}) >= 1
        assert obs.sample_value(
            samples, "kct_engine_iteration_seconds_count",
            {"model": "pair-decode0", "role": "decode"}) >= 1
        assert obs.sample_value(samples, "kct_engine_mesh_shards",
                                {"model": "pair-prefill"}) == 1
    finally:
        pair.stop()


def test_model_level_disagg_and_metadata(params):
    class _Svc:
        cfg = CFG
        ready = True
        mesh = None
        tokenizer = None

        def __init__(self, p):
            self.params = p

        def load(self):
            pass

    model = ContinuousBatchingModel(
        "lm", _Svc(params),
        EngineConfig(slots=2, max_len=64, paged=True, page_size=8,
                     role="prefill", decode_slices=1))
    model.load()
    try:
        h = model.health()
        assert h["ok"] and h["role"] == "prefill"
        meta = model.engine.debug_meta()
        assert meta["role"] == "disaggregated"
        assert meta["decode_slices"] == 1
    finally:
        model.stop()


# ---------------------------------------------------------------------------
# chaos: decode-slice death → transplant to a survivor (re-prefill)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_decode_slice_death_reprefills_on_survivor(params):
    refs = {i: greedy_ref(params, PROMPTS[i], 40) for i in range(4)}
    pair = make_pair(params, decode_slices=2)
    victim = pair.decodes[0]
    try:
        # arm the kill AFTER a couple of decode iterations so some
        # requests are mid-decode and some still queued behind them
        # kill the program the engine actually drives: the ragged
        # engine's flat-batch dispatch, else the padded decode step
        attr = "_ragged_pages" if victim._ragged else "_decode_pages"
        orig = getattr(victim, attr)
        state = {"n": 0}

        def boom(*a, **kw):
            state["n"] += 1
            if state["n"] > 2:
                raise RuntimeError("injected decode-slice death")
            return orig(*a, **kw)

        setattr(victim, attr, boom)
        reqs = {i: pair.submit(PROMPTS[i], max_new_tokens=40,
                               temperature=0.0) for i in range(4)}
        outcomes = {}
        for i, r in reqs.items():
            try:
                outcomes[i] = r.wait()
            except RetryableError as e:
                outcomes[i] = e
        ok = {i: v for i, v in outcomes.items() if isinstance(v, list)}
        failed = {i: v for i, v in outcomes.items()
                  if not isinstance(v, list)}
        # the dead slice's ACTIVE requests fail retryably (the client
        # retry path); everything that completed is token-identical
        assert failed, "the injected death should fail some actives"
        for i, toks in ok.items():
            assert toks == refs[i], f"request {i} diverged"
        # and the dead slice's QUEUED work moved to the survivor and
        # re-prefilled there (the one place reprefill_tokens may rise)
        deadline = time.monotonic() + 5
        while (pair.stats_extra["transplants"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        survivor = pair.decodes[1]
        if pair.stats_extra["transplants"]:
            assert survivor.stats["resumed"] >= 1
            assert survivor.stats["reprefill_tokens"] > 0
        assert not victim.alive
        assert pair.alive  # the pair still serves through the survivor
        post = pair.submit(PROMPTS[0], max_new_tokens=6,
                           temperature=0.0)
        assert post.wait() == greedy_ref(params, PROMPTS[0], 6)
    finally:
        pair.stop()


# ---------------------------------------------------------------------------
# fleet: roles learned from probe bodies
# ---------------------------------------------------------------------------


def test_probe_healthy_learns_role():
    body = {"models": {"lm": {"ok": True, "queue_depth": 2,
                              "heartbeat_age_s": 0.01,
                              "role": "decode"}}}
    healthy, depth, _age, role, _wv = _probe_healthy(200, body, 5.0)
    assert healthy and depth == 2 and role == "decode"
    # any admission-taking model makes the replica routable
    body["models"]["lm2"] = {"ok": True, "role": "prefill"}
    assert _probe_healthy(200, body, 5.0)[3] == "prefill"


def test_replica_health_tracks_role_and_pick_filters():
    from tests.test_fleet import FakeReplica

    cfg = FleetConfig(probe_interval_s=60.0)
    h = ReplicaHealth("r0", cfg)
    assert h.role == "colocated"
    h.note_probe(True, 0, 0.0, "decode")
    assert h.role == "decode"
    assert h.snapshot()["role"] == "decode"
    # a router never dispatches admission traffic to a decode replica
    from kubernetes_cloud_tpu.serve.fleet import FleetRouter

    r_dec = FakeReplica("dec", cfg)
    r_dec.probe_result = (200, {"models": {
        "lm": {"ok": True, "queue_depth": 0, "heartbeat_age_s": 0.01,
               "role": "decode"}}})
    r_col = FakeReplica("col", cfg)
    router = FleetRouter([r_dec, r_col], cfg)
    router.probe_now()
    assert r_dec.health.role == "decode"
    picked, _trial, skipped = router._pick([])
    assert picked is r_col
    assert not skipped  # role filtering is not a health reroute
    status, body = router._fleet_call(
        "/v1/models/lm:predict", {"instances": ["x"]})
    assert status == 200
    assert body["fleet"]["replica"] == "col"
    assert not r_dec.calls
