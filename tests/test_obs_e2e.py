"""End-to-end telemetry over the real serving stack (acceptance lock
for the unified telemetry layer): generate requests — including one
shed and one supervisor restart driven by deterministic fault
injection — flow through HTTP → engine → supervisor while the
process-global registry and the request tracer record them; /metrics
(on BOTH front-ends) renders valid Prometheus exposition covering
every family, and the trace JSONL carries correctly ordered spans."""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.obs import tracing
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
)

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _clean():
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()
    yield
    faults.uninstall()
    tracing.uninstall()
    obs.REGISTRY.reset()


@pytest.fixture(scope="module")
def service():
    svc = CausalLMService("lm", CFG,
                          params=init_params(CFG, jax.random.key(0)),
                          dtype=jnp.float32)
    svc.load()
    return svc


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _predict(port, prompt, max_new=4, headers=None, deadline_ms=None,
             timeout=60):
    payload = {"instances": [prompt],
               "parameters": {"max_new_tokens": max_new,
                              "temperature": 0.0}}
    if deadline_ms is not None:
        payload["deadline_ms"] = deadline_ms
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise TimeoutError(f"timed out waiting for {what}")


def test_full_lifecycle_metrics_and_spans(service, tmp_path):
    trace_path = str(tmp_path / "trace.jsonl")
    tracing.install(tracing.RequestTracer(trace_path))
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64))
    model.load()
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.05,
                                             hang_timeout_s=5.0))
    sup.watch(model)
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    port = server.port
    try:
        # 1. a successful generate with a client correlation id
        code, body = _predict(port, "hello telemetry", max_new=4,
                              headers={"X-Request-Id": "req-e2e-1"})
        assert code == 200
        pred = body["predictions"][0]
        assert pred["tokens_out"] == 4
        assert pred["ttft_s"] > 0  # client-visible TTFT attached

        # 2. one shed: an already-expired deadline is refused at
        # admission with 504 and lands in the shed counter + spans
        code, body = _predict(port, "shed me", deadline_ms=0,
                              headers={"X-Request-Id": "req-e2e-shed"})
        assert code == 504

        # 3. one supervisor restart: crash the decode loop via fault
        # injection; the victim 503s, the watchdog rebuilds the engine
        sup.start()
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", mode="raise")]))
        code, _ = _predict(port, "crash victim", max_new=4,
                           headers={"X-Request-Id": "req-e2e-crash"})
        assert code == 503  # retryable EngineRestartedError
        faults.uninstall()
        _wait(lambda: sup.stats["restarts"] == 1, what="restart booked")
        _wait(lambda: _get(port, "/readyz")[0] == 200,
              what="readyz recovered")
        code, _ = _predict(port, "after restart", max_new=2)
        assert code == 200  # the replacement engine serves

        # -- /metrics: valid exposition covering every serving family --
        status, text = _get(port, "/metrics")
        assert status == 200
        samples = obs.parse_text(text.decode())
        lm = {"model": "lm"}
        assert obs.sample_value(samples, "kct_engine_iterations_total",
                                lm) > 0
        assert obs.sample_value(samples, "kct_engine_tokens_total",
                                lm) >= 6
        # 2 requests reached slots (the crash victim died inside its
        # prefill, before the admitted counter — which counts requests
        # that actually entered the slot pool)
        assert obs.sample_value(samples, "kct_engine_admitted_total",
                                lm) == 2
        assert obs.sample_value(samples, "kct_engine_shed_total",
                                {"model": "lm",
                                 "reason": "deadline_admission"}) == 1
        assert obs.sample_value(samples, "kct_engine_ttft_seconds_count",
                                lm) >= 2
        assert obs.sample_value(samples, "kct_engine_slots", lm) == 2
        assert obs.sample_value(samples,
                                "kct_engine_iteration_seconds_count",
                                lm) > 0
        assert obs.sample_value(samples, "kct_supervisor_restarts_total",
                                {"model": "lm", "cause": "crash"}) == 1
        assert obs.sample_value(samples, "kct_supervisor_circuit_open",
                                lm) == 0
        assert obs.sample_value(samples, "kct_server_requests_total",
                                {"route": "predict", "status": "200"}) >= 2
        assert obs.sample_value(samples, "kct_server_requests_total",
                                {"route": "predict", "status": "504"}) == 1
        assert obs.sample_value(samples, "kct_server_requests_total",
                                {"route": "predict", "status": "503"}) == 1
        # histograms internally consistent: count == +Inf bucket
        assert obs.sample_value(
            samples, "kct_engine_ttft_seconds_count", lm) \
            == obs.sample_value(samples, "kct_engine_ttft_seconds_bucket",
                                {"model": "lm", "le": "+Inf"})
    finally:
        server.stop()
        sup.stop()
        model.stop()

    # -- trace spans: ordering + terminal states, read from the JSONL --
    from kubernetes_cloud_tpu.train.metrics import read_jsonl

    records = read_jsonl(trace_path)
    by_id = {}
    for r in records:
        by_id.setdefault(r["request_id"], []).append(r["span"])
    assert by_id["req-e2e-1"] == [
        "queued", "admitted", "prefill", "decode", "first_token",
        "complete"]
    assert by_id["req-e2e-shed"] == ["shed"]
    # the crash victim was queued (maybe admitted) then failed — its
    # stream must terminate in "failed", never "complete"
    crash = by_id["req-e2e-crash"]
    assert crash[0] == "queued" and crash[-1] == "failed"
    assert "complete" not in crash
    # per-id seq strictly increases (total order across threads)
    seqs = [r["seq"] for r in records if r["request_id"] == "req-e2e-1"]
    assert seqs == sorted(seqs)
    # terminal record carries the outcome detail
    done = [r for r in records if r["request_id"] == "req-e2e-1"][-1]
    assert done["tokens"] == 4 and done["duration_s"] > 0


def test_queued_deadline_shed_traces_and_counts(service):
    """A request whose deadline expires while QUEUED (not at admission)
    is shed by the scheduler with the deadline_queued reason."""
    from kubernetes_cloud_tpu.serve.continuous import (
        ContinuousBatchingEngine,
    )
    from kubernetes_cloud_tpu.serve.errors import DeadlineExceededError

    eng = ContinuousBatchingEngine(
        CFG, service.params, EngineConfig(slots=1, max_len=64),
        pad_token_id=0, name="lm")
    eng.start()
    try:
        with tracing.tracing() as tr:
            # occupy the single slot with a long generation…
            long = eng.submit([1, 2, 3], max_new_tokens=60,
                              temperature=0.0)
            # …so the short-deadline request expires in the queue (1 ms
            # vs 60 decode iterations — expiry is certain, not a race)
            doomed = eng.submit([4, 5], max_new_tokens=2, temperature=0.0,
                                deadline=time.monotonic() + 0.001,
                                request_id="doomed")
            with pytest.raises(DeadlineExceededError):
                doomed.wait(eng)
            long.wait(eng)
        assert [r["span"] for r in tr.spans_for("doomed")] \
            == ["queued", "shed"]
        assert tr.spans_for("doomed")[-1]["reason"] == "deadline_queued"
    finally:
        eng.stop()
    samples = obs.parse_text(obs.render_text())
    assert obs.sample_value(samples, "kct_engine_shed_total",
                            {"model": "lm",
                             "reason": "deadline_queued"}) == 1
    # KV-utilization gauge returned to 0 after the drain
    assert obs.sample_value(samples, "kct_engine_kv_utilization",
                            {"model": "lm"}) == 0


def test_native_frontend_serves_metrics(service):
    """The C++ front-end returns the same valid exposition with the
    Prometheus content type (wired through the raw-header ABI's
    hs_respond content-type argument)."""
    from kubernetes_cloud_tpu.serve import native_server

    if not native_server.available():
        pytest.skip("no C++ toolchain")
    model = ContinuousBatchingModel("lm", service, EngineConfig(
        slots=2, max_len=64))
    model.load()
    srv = native_server.NativeModelServer([model], host="127.0.0.1",
                                          port=0)
    srv.start()
    try:
        code, body = _predict(srv.port, "native telemetry", max_new=3)
        assert code == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers.get("Content-Type") == obs.CONTENT_TYPE
            samples = obs.parse_text(r.read().decode())
        assert obs.sample_value(samples, "kct_engine_tokens_total",
                                {"model": "lm"}) >= 3
        assert obs.sample_value(samples, "kct_server_requests_total",
                                {"route": "predict", "status": "200"}) == 1
    finally:
        srv.stop()
        model.stop()
