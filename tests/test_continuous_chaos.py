"""Continuous-batching chaos: clients dying mid-stream.

Mirrors ``tests/test_workflow_chaos.py`` one layer down the stack —
there the orchestrator is SIGKILLed mid-step; here a *client* dies (or
cancels) mid-generation, which is what every dropped HTTP connection /
killed pod does to a streaming LM endpoint.  The engine must reclaim
the dead request's slot and keep serving everyone else.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    ContinuousBatchingModel,
    EngineConfig,
    RequestCancelled,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_cancel_mid_stream_reclaims_slot(params):
    """Kill a client mid-stream: its slot frees immediately and the next
    queued request runs to completion unaffected."""
    eng = ContinuousBatchingEngine(
        CFG, params, EngineConfig(slots=1, max_len=64), pad_token_id=0)
    eng.start()
    try:
        victim = eng.submit(list(range(1, 9)), max_new_tokens=50,
                            temperature=0.0)
        queued = eng.submit([7, 8, 9], max_new_tokens=5, temperature=0.0)
        stream = victim.iter_tokens(timeout=60)
        next(stream)  # mid-stream: the victim occupies the only slot
        victim.cancel()
        with pytest.raises(RequestCancelled):
            for _ in stream:
                pass
        # the slot was reclaimed: the queued request finishes long before
        # the victim's 50 tokens would have
        assert len(queued.wait(eng)) == 5
        assert eng.stats["cancelled"] == 1
        # engine healthy: a fresh request still works
        again = eng.submit(list(range(20, 30)), max_new_tokens=4,
                           temperature=0.0)
        assert len(again.wait(eng)) == 4
        assert all(s is None for s in eng._slots)
    finally:
        eng.stop()


def test_cancel_queued_request_dropped_at_admission(params):
    eng = ContinuousBatchingEngine(
        CFG, params, EngineConfig(slots=1, max_len=64), pad_token_id=0)
    eng.start()
    try:
        active = eng.submit(list(range(1, 9)), max_new_tokens=20,
                            temperature=0.0)
        doomed = eng.submit([5, 6], max_new_tokens=20, temperature=0.0)
        doomed.cancel()
        with pytest.raises(RequestCancelled):
            doomed.wait(eng)
        assert len(active.wait(eng)) == 20  # bystander unaffected
        assert doomed.claimed is False  # never occupied a slot
    finally:
        eng.stop()


def test_cancelled_queued_request_frees_queue_capacity(params):
    """A cancelled request must be purged from the bounded queue even
    while every slot is busy — otherwise dead requests 503 live clients
    for the remainder of the longest running generation."""
    from kubernetes_cloud_tpu.serve.batcher import QueueFullError

    eng = ContinuousBatchingEngine(
        CFG, params, EngineConfig(slots=1, max_len=64, max_queue_size=1),
        pad_token_id=0)
    eng.start()
    try:
        active = eng.submit(list(range(1, 9)), max_new_tokens=54,
                            temperature=0.0)
        next(active.iter_tokens(timeout=60))  # slot occupied, long run
        doomed = eng.submit([5, 6], max_new_tokens=5, temperature=0.0)
        with pytest.raises(QueueFullError):
            eng.submit([1, 2], max_new_tokens=5, temperature=0.0)
        doomed.cancel()
        # capacity must open up from the purge alone, while the slot is
        # still held by the long-running request
        replacement = None
        deadline = time.monotonic() + 30
        while replacement is None and time.monotonic() < deadline:
            try:
                replacement = eng.submit([1, 2], max_new_tokens=5,
                                         temperature=0.0)
            except QueueFullError:
                time.sleep(0.002)
        assert replacement is not None
        assert not active.event.is_set()  # slot never freed in between
        with pytest.raises(RequestCancelled):
            doomed.wait(eng)
        assert len(replacement.wait(eng)) == 5
        assert len(active.wait(eng)) == 54
    finally:
        eng.stop()


def test_client_death_keeps_shared_pages_for_siblings(params):
    """Paged engine: a client dying mid-stream must release only its
    OWN page claim — prefix pages shared with a still-decoding sibling
    survive (refcounted), and the sibling's output stays
    token-identical to greedy generate."""
    from kubernetes_cloud_tpu.models.generate import generate
    import jax.numpy as jnp
    import numpy as np

    eng = ContinuousBatchingEngine(
        CFG, params, EngineConfig(slots=2, max_len=64, paged=True,
                                  page_size=8),
        pad_token_id=0)
    eng.start()
    try:
        shared = list(range(1, 17))  # 2 full pages
        victim_prompt = shared + [30]
        sibling_prompt = shared + [40]
        want = np.asarray(generate(
            CFG, params, jnp.asarray([sibling_prompt], jnp.int32),
            max_new_tokens=30, temperature=0.0, pad_token_id=0)
        )[0, len(sibling_prompt):len(sibling_prompt) + 30].tolist()

        # the victim populates the prefix cache, then dies mid-stream
        warm = eng.submit(victim_prompt, max_new_tokens=2,
                          temperature=0.0)
        assert len(warm.wait(eng)) == 2
        victim = eng.submit(victim_prompt, max_new_tokens=40,
                            temperature=0.0)
        sibling = eng.submit(sibling_prompt, max_new_tokens=30,
                             temperature=0.0)
        vstream = victim.iter_tokens(timeout=60)
        next(vstream)
        sstream = sibling.iter_tokens(timeout=60)
        next(sstream)  # sibling admitted: shares the 2 prefix pages
        shared_pages = eng._slot_pages[eng._slots.index(sibling)][:2]
        assert all(eng.allocator.refcount(p) >= 2 for p in shared_pages)

        victim.cancel()
        with pytest.raises(RequestCancelled):
            for _ in vstream:
                pass
        # victim's claim is gone, but the sibling still pins the shared
        # prefix pages — they must NOT have been freed or recycled
        deadline = time.monotonic() + 30
        while victim in eng._slots and time.monotonic() < deadline:
            time.sleep(0.01)
        assert victim not in eng._slots
        assert all(eng.allocator.refcount(p) >= 1 for p in shared_pages)

        assert sibling.wait(eng) == want, "sibling corrupted by cancel"
    finally:
        eng.stop()


def test_sigkilled_http_client_does_not_poison_server(params):
    """SIGKILL a real HTTP client mid-request (the workflow-chaos
    pattern): the server thread finishes the orphaned generation, the
    slot frees, and subsequent requests are unaffected."""
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    m = ContinuousBatchingModel("lm", svc, EngineConfig(slots=2, max_len=64))
    m.load()
    server = ModelServer([m], host="127.0.0.1", port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/v1/models/lm:predict"
        client = (
            "import urllib.request, json\n"
            f"req = urllib.request.Request({url!r}, data=json.dumps("
            "{'instances': ['a long doomed prompt'], 'parameters': "
            "{'max_new_tokens': 50, 'temperature': 0.0}}).encode(), "
            "headers={'Content-Type': 'application/json'})\n"
            "urllib.request.urlopen(req, timeout=120).read()\n")
        p = subprocess.Popen([sys.executable, "-c", client])
        time.sleep(0.5)  # let the request reach the engine
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

        # the server must keep answering while/after the orphan drains
        req = urllib.request.Request(
            url, data=json.dumps({
                "instances": ["survivor"],
                "parameters": {"max_new_tokens": 4, "temperature": 0.0},
            }).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["predictions"][0]["tokens_out"] == 4

        # orphaned generation runs to completion, then its slot frees
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(s is None for s in m.engine._slots):
                break
            time.sleep(0.1)
        assert all(s is None for s in m.engine._slots)
    finally:
        server.stop()
        m.stop()
