"""Stable Diffusion family: models, datasets, trainer, serving."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.data.diffusion import (
    DreamBoothDataset,
    LocalBase,
    PromptDataset,
    collate_dreambooth,
    collate_images,
)
from kubernetes_cloud_tpu.models.diffusion import (
    CLIPTextConfig,
    NoiseSchedule,
    UNetConfig,
    VAEConfig,
    add_noise,
    make_schedule,
    unet_apply,
    unet_init,
    vae_decode,
    vae_encode,
    vae_init,
)
from kubernetes_cloud_tpu.models.diffusion.schedule import ddim_step, pred_x0
from kubernetes_cloud_tpu.train.sd_trainer import (
    SDTrainerConfig,
    StableDiffusionTrainer,
    ema_decay_schedule,
    ema_update,
)

TINY_UNET = UNetConfig(block_out_channels=(16, 32), layers_per_block=1,
                       cross_attn_dim=16, num_heads=2, norm_groups=8,
                       dtype=jnp.float32)
TINY_VAE = VAEConfig(block_out_channels=(16, 32), norm_groups=8,
                     latent_channels=4)
TINY_CLIP = CLIPTextConfig(vocab_size=128, hidden_size=16, num_layers=2,
                           num_heads=2, max_length=8, dtype=jnp.float32)


def _write_images(tmp_path, n=4, size=32, captions=True):
    from PIL import Image

    d = tmp_path / "imgs"
    d.mkdir(exist_ok=True)
    rng = np.random.RandomState(0)
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"img{i}.png")
        if captions:
            (d / f"img{i}.txt").write_text(f"a photo number {i}")
    return str(d)


# -- schedule ---------------------------------------------------------------

def test_schedule_roundtrip():
    sched = make_schedule(NoiseSchedule())
    x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 4))
    noise = jax.random.normal(jax.random.key(1), (2, 4, 4, 4))
    t = jnp.array([100, 900])
    xt = add_noise(sched, x0, noise, t)
    rec = pred_x0(sched, noise, xt, t)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x0), atol=2e-3)


def test_ddim_denoises_with_oracle_eps():
    """Stepping DDIM with the true noise recovers x0."""
    sched = make_schedule(NoiseSchedule())
    x0 = jax.random.normal(jax.random.key(2), (1, 4, 4, 4))
    noise = jax.random.normal(jax.random.key(3), (1, 4, 4, 4))
    t = jnp.array([500])
    xt = add_noise(sched, x0, noise, t)
    final = ddim_step(sched, noise, xt, t, jnp.array([-1]))
    np.testing.assert_allclose(np.asarray(final), np.asarray(x0), atol=2e-3)


# -- datasets ---------------------------------------------------------------

def test_local_base_pairs_and_ucg(tmp_path):
    root = _write_images(tmp_path)
    ds = LocalBase(root, size=16, ucg=0.0)
    assert len(ds) == 4
    row = ds[1]
    assert row["image"].shape == (16, 16, 3)
    assert row["caption"] == "a photo number 1"
    assert row["image"].min() >= -1.0 and row["image"].max() <= 1.0

    ds_ucg = LocalBase(root, size=16, ucg=1.0, seed=0)
    assert ds_ucg[0]["caption"] == ""  # always dropped at ucg=1

    batch = collate_images([ds[i] for i in range(4)])
    assert batch["images"].shape == (4, 16, 16, 3)
    assert len(batch["captions"]) == 4


def test_dreambooth_dataset(tmp_path):
    inst = _write_images(tmp_path, n=2, captions=False)
    cls_dir = tmp_path / "cls"
    cls_dir.mkdir()
    ds = DreamBoothDataset(inst, "a sks dog", str(cls_dir), "a dog",
                           size=16, num_class_images=3)
    assert ds.missing_class_images == 3
    assert not ds.with_prior

    from PIL import Image

    for i in range(3):
        Image.fromarray(np.zeros((16, 16, 3), np.uint8)).save(
            cls_dir / f"c{i}.png")
    ds = DreamBoothDataset(inst, "a sks dog", str(cls_dir), "a dog",
                           size=16, num_class_images=3)
    assert ds.with_prior and ds.missing_class_images == 0
    batch = collate_dreambooth([ds[0], ds[1]])
    # [instance x2; class x2]
    assert batch["images"].shape == (4, 16, 16, 3)
    assert batch["captions"][:2] == ["a sks dog"] * 2
    assert batch["captions"][2:] == ["a dog"] * 2

    pd = PromptDataset("a dog", 5)
    assert len(pd) == 5 and pd[3] == {"prompt": "a dog", "index": 3}


# -- EMA --------------------------------------------------------------------

def test_ema_warmup_schedule():
    assert float(ema_decay_schedule(jnp.asarray(0.0), 0.9999)) == pytest.approx(0.1)
    assert float(ema_decay_schedule(jnp.asarray(1e7), 0.9999)) == pytest.approx(0.9999)
    ema = {"w": jnp.ones((2,))}
    cur = {"w": jnp.zeros((2,))}
    out = ema_update(ema, cur, 0.9)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.9)


# -- trainer ----------------------------------------------------------------

def _trainer(tmp_path, dataset, collate, devices, **kw):
    mesh = build_mesh(MeshSpec(data=2), devices=devices[:2])
    defaults = dict(run_name="sd1", output_path=str(tmp_path), batch_size=2,
                    lr=1e-4, epochs=1, save_steps=0, image_log_steps=0,
                    resolution=32, use_ema=True,
                    logs=str(tmp_path / "logs"))
    defaults.update(kw)
    return StableDiffusionTrainer(
        SDTrainerConfig(**defaults), mesh, dataset, collate,
        unet_cfg=TINY_UNET, vae_cfg=TINY_VAE, clip_cfg=TINY_CLIP)


def test_sd_train_loop_and_checkpoint(tmp_path, devices8):
    root = _write_images(tmp_path)
    ds = LocalBase(root, size=32, ucg=0.5, seed=0)
    trainer = _trainer(tmp_path, ds, collate_images, devices8)
    result = trainer.train()
    assert result["steps"] == 2  # 4 imgs / bs 2
    assert np.isfinite(result["train/loss"])
    final = result["final_dir"]
    for mod in ("unet", "vae", "encoder"):
        assert os.path.exists(os.path.join(final, f"{mod}.tensors"))
    assert os.path.exists(os.path.join(final, ".ready.txt"))


def test_sd_dreambooth_prior_loss(tmp_path, devices8):
    inst = _write_images(tmp_path, n=2, captions=False)
    cls_dir = tmp_path / "cls"
    cls_dir.mkdir()
    from PIL import Image

    for i in range(2):
        Image.fromarray(np.zeros((32, 32, 3), np.uint8)).save(
            cls_dir / f"c{i}.png")
    ds = DreamBoothDataset(inst, "a sks dog", str(cls_dir), "a dog",
                           size=32, num_class_images=2)
    trainer = _trainer(tmp_path, ds, collate_dreambooth, devices8,
                       run_name="db1", prior_loss_weight=1.0, batch_size=1)
    result = trainer.train()
    assert "train/prior_loss" in result
    assert np.isfinite(result["train/prior_loss"])


def test_sd_v_prediction_changes_target(tmp_path, devices8):
    root = _write_images(tmp_path)
    ds = LocalBase(root, size=32, ucg=0.0, seed=0)
    t_eps = _trainer(tmp_path, ds, collate_images, devices8,
                     run_name="eps", use_ema=False)
    t_v = _trainer(tmp_path, ds, collate_images, devices8,
                   run_name="v", use_ema=False, v_prediction=True)
    r_eps = t_eps.train()
    r_v = t_v.train()
    assert r_eps["train/loss"] != r_v["train/loss"]


# -- serving ----------------------------------------------------------------

def test_sd_service_roundtrip(tmp_path, devices8):
    import base64

    root = _write_images(tmp_path)
    ds = LocalBase(root, size=32, ucg=0.0, seed=0)
    trainer = _trainer(tmp_path, ds, collate_images, devices8,
                       run_name="srv")
    trainer.train()

    from kubernetes_cloud_tpu.serve.sd_service import StableDiffusionService

    svc = StableDiffusionService(
        "sd", os.path.join(str(tmp_path), "results-srv", "final"))
    svc.load()
    assert svc.ready
    out = svc.predict({
        "prompt": "a test",
        "parameters": {"height": 32, "width": 32,
                       "num_inference_steps": 3, "seed": 7},
    })
    pred = out["predictions"][0]
    assert pred["format"] == "png"
    png = base64.b64decode(pred["image_b64"])
    from PIL import Image
    import io

    img = Image.open(io.BytesIO(png))
    assert img.size == (32, 32)
