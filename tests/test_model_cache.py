"""Lifecycle-managed model registry (serve/model_cache.py).

The cache replaces ``ModelServer``'s static ``{name: Model}`` dict: a
model moves ``loading → active → draining → retired`` (terminal
``failed`` for a load that raised), ``capacity`` pages the
least-recently-used idle model out through its drain path, and tenant
quotas stop one tenant from evicting everyone else's adapters.  The
server-visible consequences ride along: ``load_all`` continues past a
bad model instead of leaving the registry half-populated, ``/readyz``
reports the failure per-model, and ``/v1/models/<name>`` merges the
lifecycle snapshot into the readiness body.
"""

import json
import threading

import pytest

from kubernetes_cloud_tpu.serve.errors import (
    ModelCacheFullError,
    TenantQuotaError,
)
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.model_cache import ModelCache
from kubernetes_cloud_tpu.serve.server import ModelServer

pytestmark = pytest.mark.swap


class Toy(Model):
    """Instrumented predictor: scriptable load failure, drain witness."""

    def __init__(self, name, *, fail=False, version=None):
        super().__init__(name)
        self._fail = fail
        self.weights_version = version
        self.stopped = False

    def load(self):
        if self._fail:
            raise RuntimeError(f"weights for {self.name} unreadable")
        self.ready = True

    def predict(self, payload):
        return {"model": self.name, "echo": payload.get("x")}

    def stop(self):
        self.stopped = True
        self.ready = False


# -- lifecycle states --------------------------------------------------------


def test_states_walk_the_lifecycle():
    cache = ModelCache([Toy("m")])
    assert cache.states() == {"m": "loading"}
    cache.load("m")
    assert cache.states() == {"m": "active"}
    assert cache["m"].ready
    cache.evict("m")
    # retired: metadata survives, the registry dict no longer serves it
    assert cache.states() == {"m": "retired"}
    assert "m" not in cache
    assert cache.entry("m").model.ready is False


def test_failed_load_is_terminal_and_stays_registered():
    cache = ModelCache([Toy("bad", fail=True)])
    with pytest.raises(RuntimeError, match="unreadable"):
        cache.load("bad")
    entry = cache.entry("bad")
    assert entry.state == "failed"
    assert "unreadable" in entry.error
    # the name still resolves — readiness can report WHY, and load_all
    # does not retry a terminal failure
    assert "bad" in cache
    assert cache.load_all() == {}
    assert entry.state == "failed"


def test_evict_drains_through_stop_and_allows_readmission():
    m = Toy("m")
    cache = ModelCache([m])
    cache.load("m")
    cache.evict("m")
    assert m.stopped and not m.ready
    # a retired name can be admitted again (rollout round-trip)
    cache.admit(Toy("m"))
    assert cache.states()["m"] == "loading"


def test_double_admit_rejected():
    cache = ModelCache([Toy("m")])
    with pytest.raises(ValueError, match="already"):
        cache.admit(Toy("m"))


# -- LRU paging --------------------------------------------------------------


def _loaded(name):
    m = Toy(name)
    m.load()
    return m


def test_capacity_evicts_least_recently_used():
    cache = ModelCache(capacity=2)
    a, b = _loaded("a"), _loaded("b")
    cache.admit(a)
    cache.admit(b)
    cache.touch("a")  # b is now the LRU model
    cache.admit(_loaded("c"))
    assert "b" not in cache and b.stopped
    assert set(cache) == {"a", "c"}
    assert cache.states()["b"] == "retired"


def test_busy_models_are_never_paged_out():
    cache = ModelCache(capacity=1)
    cache.admit(_loaded("a"))
    with cache.using("a"):  # in-flight request pins it
        with pytest.raises(ModelCacheFullError, match="busy"):
            cache.admit(_loaded("b"))
        assert "a" in cache
    # once idle the same admit succeeds and pages "a" out
    cache.admit(_loaded("b"))
    assert set(cache) == {"b"}


def test_using_counts_inflight_and_touches_lru():
    cache = ModelCache([_loaded("m")])
    entry = cache.entry("m")
    before = entry.last_used
    with cache.using("m"):
        assert entry.inflight == 1
        with cache.using("m"):
            assert entry.inflight == 2
    assert entry.inflight == 0
    assert entry.last_used >= before


# -- tenancy -----------------------------------------------------------------


def test_tenant_quota_bounds_one_tenants_zoo():
    cache = ModelCache(tenant_model_quota=1)
    cache.admit(_loaded("a1"), tenant="acme")
    with pytest.raises(TenantQuotaError, match="acme"):
        cache.admit(_loaded("a2"), tenant="acme")
    # another tenant (and the operator's untenanted models) are not
    # collateral damage
    cache.admit(_loaded("b1"), tenant="other")
    cache.admit(_loaded("ops"))
    # retiring frees the quota slot
    cache.evict("a1")
    cache.admit(_loaded("a2"), tenant="acme")


# -- the server riding the cache ---------------------------------------------


def _get(server, path):
    status, obj = server.handle("GET", path, b"")
    return status, obj


def _post(server, path, payload):
    return server.handle("POST", path, json.dumps(payload).encode())


def test_load_all_serves_degraded_past_a_bad_model():
    srv = ModelServer([Toy("good"), Toy("bad", fail=True)],
                      host="127.0.0.1", port=0)
    srv.load_all()  # must NOT raise: one model made it
    status, body = _get(srv, "/readyz")
    assert status == 503 and body["status"] == "unready"
    assert body["models"]["good"]["ok"]
    bad = body["models"]["bad"]
    assert not bad["ok"]
    assert bad["state"] == "failed" and "unreadable" in bad["error"]
    # the good model serves; the failed one answers a typed 503
    status, body = _post(srv, "/v1/models/good:predict", {"x": 1})
    assert status == 200 and body["echo"] == 1
    status, body = _post(srv, "/v1/models/bad:predict", {"x": 1})
    assert status == 503 and body["error_kind"] == "ModelLoadFailed"


def test_load_all_raises_when_nothing_loaded():
    srv = ModelServer([Toy("bad", fail=True)], host="127.0.0.1", port=0)
    with pytest.raises(RuntimeError, match="no model loaded"):
        srv.load_all()


def test_model_detail_merges_lifecycle_snapshot():
    srv = ModelServer([Toy("m", version="abcdef123456")],
                      host="127.0.0.1", port=0)
    srv.load_all()
    status, body = _get(srv, "/v1/models/m")
    assert status == 200
    assert body == {"name": "m", "ready": True, "state": "active",
                    "weights_version": "abcdef123456"}


def test_server_accepts_prebuilt_cache_with_quota():
    cache = ModelCache([Toy("m")], capacity=4, tenant_model_quota=2)
    srv = ModelServer(cache, host="127.0.0.1", port=0)
    assert srv.models is cache
    srv.load_all()
    status, body = _get(srv, "/readyz")
    assert status == 200 and body["models"]["m"]["state"] == "active"


def test_concurrent_using_is_thread_safe():
    cache = ModelCache([_loaded("m")])
    n, rounds = 8, 200

    def worker():
        for _ in range(rounds):
            with cache.using("m"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert cache.entry("m").inflight == 0
