"""Live weight hot-swap chaos: drain/transplant rollouts under faults.

The proof for ``ContinuousBatchingModel.swap_weights``: a running model
rolls onto new weights with zero dropped requests (queued work
transplants, in-flight slots finish on the weights that prefilled
them); a corrupt candidate or an injected ``weights.swap`` fault rolls
back whole — the old version never stops serving and the prepared side
is discarded; a second swap while one is in flight answers a typed 503;
and a supervisor restart landing mid-swap converges to exactly ONE live
engine (the ``_swap_lock`` cutover serialization).  The identity trail
rides along: ``weights_version`` changes across the swap in /readyz,
per-prediction responses, fleet probe learning, and the native
front-end, so a rollout is observable end to end.
"""

import dataclasses
import json
import shutil
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.errors import (
    EngineRestartedError,
    SwapInProgressError,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
    _EngineTarget,
)
from kubernetes_cloud_tpu.weights.tensorstream import (
    read_index,
    weights_version,
    write_pytree,
)

pytestmark = [pytest.mark.swap, pytest.mark.chaos]

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    inj = faults.active()
    if inj is not None:
        inj.release()
    faults.uninstall()


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Two distinct versioned artifacts of the same architecture —
    the old rollout and the candidate."""
    d = tmp_path_factory.mktemp("weights")
    v1, v2 = str(d / "v1.tensors"), str(d / "v2.tensors")
    write_pytree(v1, init_params(CFG, jax.random.key(0)))
    write_pytree(v2, init_params(CFG, jax.random.key(1)))
    ver1 = weights_version(read_index(v1))
    ver2 = weights_version(read_index(v2))
    assert ver1 != ver2
    return {"v1": v1, "v2": v2, "ver1": ver1, "ver2": ver2}


@pytest.fixture
def model(artifacts):
    """A serving model streamed from the v1 artifact (so its
    weights_version is the content hash, not None)."""
    svc = CausalLMService("lm", CFG, weights_path=artifacts["v1"],
                          dtype=jnp.float32)
    m = ContinuousBatchingModel("lm", svc,
                                EngineConfig(slots=2, max_len=96))
    m.load()
    # compile the programs the scenario will hit before arming faults
    m.engine.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait()
    yield m
    m.stop()


def _predict(port, prompt, max_new, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps({
            "instances": [prompt],
            "parameters": {"max_new_tokens": max_new, "temperature": 0.0},
        }).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _swap(port, weights):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:swap",
        data=json.dumps({"weights": weights}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _readyz_model(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=10) as r:
        return json.loads(r.read())["models"]["lm"]


def test_hot_swap_under_traffic_drops_nothing(model, artifacts):
    """ISSUE acceptance: swap weights on a model taking continuous
    traffic — every client request succeeds (queued work transplants
    to the new engine, in-flight slots drain on the old), and the
    weights_version trail flips everywhere at once."""
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    stop = threading.Event()
    results, failures = [], []

    def client():
        while not stop.is_set():
            try:
                status, body = _predict(server.port, "rolling rollout", 4)
                results.append((status, body["predictions"][0]))
            except Exception as e:  # noqa: BLE001 - the assertion target
                failures.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(3)]
    try:
        assert _readyz_model(server.port)["weights_version"] \
            == artifacts["ver1"]
        for t in threads:
            t.start()
        status, body = _swap(server.port, artifacts["v2"])
        assert status == 200, body
        assert body["swapped"] is True
        assert body["weights_version"] == artifacts["ver2"]
        # a post-swap prediction carries the new identity
        _, after = _predict(server.port, "rolling rollout", 4)
        assert after["predictions"][0]["weights_version"] \
            == artifacts["ver2"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        server.stop()
    assert not failures, failures
    assert results and all(s == 200 for s, _ in results)
    # every prediction names the weights that produced it — one of the
    # two versions, never an unlabeled tear
    seen = {p["weights_version"] for _, p in results}
    assert seen <= {artifacts["ver1"], artifacts["ver2"]}
    assert model.weights_version == artifacts["ver2"]
    assert model.engine.weights_version == artifacts["ver2"]


def test_corrupt_candidate_rolls_back_409(model, artifacts, tmp_path):
    """A candidate artifact with a flipped byte never takes traffic:
    the chunk crc32 catches it during prepare, the route answers 409
    with ``rolled_back: true``, and the old version keeps serving."""
    bad = str(tmp_path / "bad.tensors")
    shutil.copyfile(artifacts["v2"], bad)
    idx = read_index(bad)
    victim = idx["data_start"] + 64
    with open(bad, "r+b") as f:
        f.seek(victim)
        byte = f.read(1)
        f.seek(victim)
        f.write(bytes([byte[0] ^ 0xFF]))
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        old_engine = model.engine
        status, body = _swap(server.port, bad)
        assert status == 409
        assert body["rolled_back"] is True
        assert body["error_kind"] == "WeightIntegrityError"
        assert body["weights_version"] == artifacts["ver1"]
        # the old engine object itself is still the serving one
        assert model.engine is old_engine and model.engine.alive
        status, out = _predict(server.port, "still the old weights", 4)
        assert status == 200
        assert out["predictions"][0]["weights_version"] \
            == artifacts["ver1"]
    finally:
        server.stop()


def test_swap_fault_after_prepare_rolls_back_whole(model, artifacts):
    """``weights.swap`` fires in the worst window — the new engine is
    fully prepared and started, one instant before cutover.  Rollback
    discards the prepared side whole; the lock is released so a retry
    succeeds."""
    old_engine = model.engine
    faults.install(faults.FaultInjector([FaultSpec("weights.swap")]))
    with pytest.raises(faults.FaultError):
        model.swap_weights(artifacts["v2"])
    assert model.engine is old_engine and model.engine.alive
    assert model.weights_version == artifacts["ver1"]
    assert model.service.weights_path == artifacts["v1"]
    # service params were not torn mid-rollback: the old engine still
    # generates
    assert len(model.engine.submit([5, 6], max_new_tokens=3,
                                   temperature=0.0).wait()) == 3
    faults.uninstall()
    out = model.swap_weights(artifacts["v2"])
    assert out["weights_version"] == artifacts["ver2"]
    assert not old_engine.alive  # drained after the committed swap


def test_concurrent_swap_rejected_typed_503(model, artifacts):
    """Swaps serialize: while one is in flight a second answers the
    retryable ``SwapInProgressError`` (503 over HTTP) instead of
    queueing a multi-second weight load behind the first."""
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        assert model._swapping.acquire(blocking=False)
        try:
            with pytest.raises(SwapInProgressError):
                model.swap_weights(artifacts["v2"])
            status, body = _swap(server.port, artifacts["v2"])
            assert status == 503
            assert body["error_kind"] == "SwapInProgressError"
        finally:
            model._swapping.release()
        status, body = _swap(server.port, artifacts["v2"])
        assert status == 200 and body["swapped"] is True
    finally:
        server.stop()


def test_supervisor_restart_mid_swap_converges_to_one_engine(
        model, artifacts):
    """The interleave the ``_swap_lock`` exists for: a watchdog restart
    lands while a swap sleeps between prepare and cutover.  Whichever
    side wins the lock, the process converges to exactly one live
    engine serving the new version — never a torn half of each."""
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=60.0))
    sup.watch(model)  # installs model.supervisor (no watchdog thread:
    # the restart is driven synchronously below, determinism over dice)
    e0 = model.engine
    inj = faults.install(faults.FaultInjector(
        [FaultSpec("weights.swap", mode="slow", delay_s=1.0)]))
    swap_result: dict = {}

    def swapper():
        try:
            swap_result["out"] = model.swap_weights(artifacts["v2"])
        except Exception as e:  # noqa: BLE001 - inspected below
            swap_result["err"] = e

    t = threading.Thread(target=swapper)
    t.start()
    try:
        # the swap thread is parked inside the slow fault, new engine
        # prepared, cutover not yet taken
        deadline = 10.0
        while not inj.fired and deadline > 0:
            threading.Event().wait(0.01)
            deadline -= 0.01
        assert inj.fired, "swap never reached the weights.swap site"
        # the production restart path (what the watchdog thread runs)
        _EngineTarget(model).restart(
            EngineRestartedError("lm: injected mid-swap restart"))
    finally:
        t.join(timeout=60)
    assert "err" not in swap_result, swap_result
    assert swap_result["out"]["weights_version"] == artifacts["ver2"]
    # converged: the current engine is alive on v2; the pre-swap engine
    # and the restart-built interim engine are both stopped
    assert model.engine.alive
    assert model.engine.weights_version == artifacts["ver2"]
    assert not e0.alive
    assert len(model.engine.submit([7, 8], max_new_tokens=3,
                                   temperature=0.0).wait()) == 3


def test_weights_version_parity_on_native_front_end():
    """The C++ front-end routes through the same ``handle()``, so the
    rollout identity a fleet probe reads is byte-identical across
    front-ends."""
    from kubernetes_cloud_tpu.serve import native_server
    from kubernetes_cloud_tpu.serve.model import Model
    from kubernetes_cloud_tpu.serve.native_server import NativeModelServer

    if not native_server.available():
        pytest.skip("native front-end toolchain unavailable")

    class Versioned(Model):
        weights_version = "cafebabe0123"

        def predict(self, payload):
            return {"predictions": []}

    stdlib = ModelServer([Versioned("lm")], host="127.0.0.1", port=0)
    stdlib.load_all()
    native = NativeModelServer([Versioned("lm")], host="127.0.0.1",
                               port=0)
    native.load_all()
    native.start()
    try:
        want = stdlib._readyz()[1]["models"]["lm"]["weights_version"]
        assert want == "cafebabe0123"
        got = _readyz_model(native.port)["weights_version"]
        assert got == want
    finally:
        native.stop()


def test_fleet_probe_learns_weights_versions():
    """Probe bodies teach the router which replicas have rolled onto
    the new artifact — the mid-rollout observability the fleet needs
    to tell an already-swapped replica from a laggard."""
    from kubernetes_cloud_tpu.serve.fleet import (
        FleetConfig,
        FleetRouter,
        Replica,
    )

    class Scripted(Replica):
        def __init__(self, rid, cfg, version):
            super().__init__(rid, cfg)
            self.version = version

        def probe(self, timeout):
            return 200, {"status": "ready", "models": {
                "lm": {"ok": True, "queue_depth": 0,
                       "heartbeat_age_s": 0.01,
                       "weights_version": self.version}}}

        def call(self, method, path, body, headers=None):
            return 200, {}

    cfg = FleetConfig(dispatch_timeout_s=5.0)
    reps = [Scripted("old", cfg, "aaaaaaaaaaaa"),
            Scripted("new", cfg, "bbbbbbbbbbbb")]
    router = FleetRouter(reps, cfg, host="127.0.0.1", port=0)
    router.probe_now()
    assert reps[0].health.weights_versions == {"lm": "aaaaaaaaaaaa"}
    assert reps[1].health.weights_versions == {"lm": "bbbbbbbbbbbb"}
    snaps = {s["id"]: s for s in router.snapshot()["replicas"]}
    assert snaps["old"]["weights_versions"]["lm"] == "aaaaaaaaaaaa"
    assert snaps["new"]["weights_versions"]["lm"] == "bbbbbbbbbbbb"
    # a replica mid-swap (rolled) updates on the next probe pass
    reps[0].version = "bbbbbbbbbbbb"
    router.probe_now()
    assert reps[0].health.weights_versions == {"lm": "bbbbbbbbbbbb"}
