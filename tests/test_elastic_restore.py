"""Elastic checkpoint restore: save on one mesh, restore on another.

The preemption story (SURVEY §5.3) is only as good as resume when the
replacement slice differs — e.g. a v5e-8 training job preempted and
resumed on a v5e-4.  Orbax's StandardRestore reshards transparently when
the restore template carries the new mesh's shardings; these tests pin
that contract for both shrink (8 -> 4) and re-partition (dp -> tp)
cases, exceeding the reference (whose DeepSpeed/torch checkpoints are
world-size-locked, ``kubeflow/training-operator/gpt-neox/``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models.causal_lm import PRESETS
from kubernetes_cloud_tpu.parallel.sharding import (
    logical_to_physical,
    param_specs,
)
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_optimizer,
)
from kubernetes_cloud_tpu.weights.checkpoint import Checkpointer

pytestmark = pytest.mark.slow

CFG = dataclasses.replace(PRESETS["test-tiny"], num_layers=2)
TRAIN = TrainConfig(total_steps=10)


def _abstract_state(mesh):
    optimizer = make_optimizer(TRAIN)

    def init():
        from kubernetes_cloud_tpu.models.causal_lm import init_params

        params = init_params(CFG, jax.random.key(0))
        return {"params": params, "opt_state": optimizer.init(params),
                "step": jnp.zeros((), jnp.int32)}

    shapes = jax.eval_shape(init)
    shardings = logical_to_physical(param_specs(shapes), mesh)
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


def _values(tree):
    return {jax.tree_util.keystr(p): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(tree)[0]}


@pytest.mark.parametrize("save_spec,restore_spec", [
    # shrink: 8 devices (dp4 x fsdp2) -> 4 devices (dp2 x fsdp2)
    (MeshSpec(data=4, fsdp=2), MeshSpec(data=2, fsdp=2)),
    # re-partition: pure data-parallel -> tensor-parallel
    (MeshSpec(data=8), MeshSpec(data=2, model=2)),
])
def test_restore_onto_different_mesh(tmp_path, save_spec, restore_spec,
                                     devices8):
    save_mesh = build_mesh(save_spec, devices=devices8)
    state = init_train_state(CFG, TRAIN, jax.random.key(1), save_mesh)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(0, state)
    ck.wait()
    want = _values(state)

    restore_mesh = build_mesh(restore_spec, devices=devices8[:4])
    template = _abstract_state(restore_mesh)
    restored = ck.restore(template, step=0)
    ck.close()

    got = _values(restored)
    assert want.keys() == got.keys()
    for key in want:
        np.testing.assert_array_equal(want[key], got[key], err_msg=key)
    # the restored arrays really live on the new mesh's shardings
    leaf = restored["params"]["blocks"]["attn"]["wqkv"]
    assert leaf.sharding.mesh.devices.size == restore_mesh.devices.size


def test_restore_same_mesh_roundtrip(tmp_path, devices8):
    mesh = build_mesh(MeshSpec(data=2, fsdp=2), devices=devices8[:4])
    state = init_train_state(CFG, TRAIN, jax.random.key(2), mesh)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(3, state)
    ck.wait()
    restored = ck.restore(_abstract_state(mesh))
    ck.close()
    for key, val in _values(state).items():
        np.testing.assert_array_equal(val, _values(restored)[key],
                                      err_msg=key)
