"""Fleet-router chaos: the ISSUE's acceptance scenarios over real
engines.

* a replica SIGKILLed mid-stream → the request completes via retry on
  a peer, token-identical to one-shot greedy ``generate``;
* a hung replica is ejected (dispatch timeout + stale-heartbeat probe)
  and recovered through the half-open trial once the hang releases;
* a 3-replica rolling restart under sustained load finishes with ZERO
  failed requests (queued work transplanted through the router);
* ``fleet.dispatch`` / ``fleet.probe`` hold the raise/hang containment
  contract.

Deterministic throughout: the injector fires on exact hit counts, and
the router's pick order is pinned by probing/queue-depth state — never
timing dice.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.errors import EngineRestartedError
from kubernetes_cloud_tpu.serve.fleet import (
    ACTIVE,
    EJECTED,
    HALF_OPEN,
    FleetConfig,
    FleetRouter,
    LocalReplica,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer

pytestmark = [pytest.mark.chaos, pytest.mark.fleet]

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def service(params):
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    return svc


def make_fleet(service, n, fcfg, engine_kw=None):
    """N in-process replicas (each its own engine over the shared
    weights) behind one router.  Engines are warmed by the caller."""
    kw = {"slots": 2, "max_len": 96}
    kw.update(engine_kw or {})
    replicas = []
    for i in range(n):
        model = ContinuousBatchingModel("lm", service,
                                        EngineConfig(**kw))
        model.load()
        server = ModelServer([model], host="127.0.0.1", port=0)
        replicas.append(LocalReplica(f"r{i}", server, fcfg))
    router = FleetRouter(replicas, fcfg, host="127.0.0.1", port=0)
    return router, replicas


def warm_all(replicas):
    """Compile every program each engine will hit BEFORE arming
    faults: a first-iteration XLA compile is indistinguishable from a
    wedge, and these tests are about injected failures."""
    for r in replicas:
        eng = r.server.models["lm"].engine
        eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait()


def shutdown(router):
    router.shutdown()


def _predict(port, prompt, max_new, timeout=60, rid=None):
    payload = {"instances": [prompt],
               "parameters": {"max_new_tokens": max_new,
                              "temperature": 0.0}}
    if rid:
        payload["request_id"] = rid
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def greedy_reference(service, prompt, n):
    opts = {"MAX_NEW_TOKENS": n, "TEMPERATURE": 0.0, "TOP_K": 0,
            "TOP_P": 1.0, "SEED": 0, "ECHO_PROMPT": False}
    return service.generate_texts([prompt], opts)[0]


def _wait_until(cond, timeout=15.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def test_replica_killed_mid_stream_completes_via_retry_token_identical(
        service):
    """ISSUE acceptance: the serving replica crashes mid-generation
    (decode program dies — the in-process SIGKILL) → the router
    retries the request on a peer → the client sees ONE 200 whose
    output is token-identical to one-shot greedy generate."""
    fcfg = FleetConfig(dispatch_timeout_s=30.0, probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg)
    warm_all(replicas)
    router.start()
    try:
        want = greedy_reference(service, "after the storm", 6)
        # crash the SECOND decode iteration of whichever engine takes
        # the request: one token is already out internally (mid-
        # stream), none was delivered to the client (buffered JSON) —
        # the retry is safe and must reproduce the exact tokens
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", at=2, times=1)]))
        status, obj = _predict(router.port, "after the storm", 6)
        assert status == 200
        pred = obj["predictions"][0]
        assert pred["generated_text"] == want  # token-identical
        assert obj["fleet"]["retried_ok"] is True
        assert obj["fleet"]["dispatches"] == 2
        assert router.stats["retried_ok"] == 1
        # exactly one engine died; the fleet stayed available
        dead = [r for r in replicas
                if not r.server.models["lm"].engine.alive]
        assert len(dead) == 1
    finally:
        faults.uninstall()
        shutdown(router)


def test_hung_replica_ejected_then_recovered_via_half_open(service):
    """ISSUE acceptance: a wedged replica (decode hang) times out the
    dispatch → retry succeeds on the peer → the hung replica is
    ejected; its stale heartbeat keeps probes failing while wedged;
    once the hang releases, a probe success takes it to half-open and
    the next dispatched request is the trial that reinstates it."""
    fcfg = FleetConfig(dispatch_timeout_s=1.0, timeout_eject=1,
                       probe_interval_s=30.0,  # probes driven by hand
                       heartbeat_stale_s=0.5,
                       probe_fail_threshold=1)
    router, replicas = make_fleet(service, 2, fcfg)
    warm_all(replicas)
    router.start()
    victim = replicas[0]  # equal load scores: list order breaks the tie
    try:
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", mode="hang", at=1, times=1,
                       delay_s=60.0)]))
        status, obj = _predict(router.port, "wedge me", 6, timeout=30)
        assert status == 200  # retried onto the healthy peer
        assert obj["fleet"]["retried_ok"] is True
        assert obj["fleet"]["replica"] == "r1"
        assert victim.health.state == EJECTED
        assert victim.health.snapshot()["ejected_cause"] == "timeouts"
        # wedged: the heartbeat is stale, so probes must NOT half-open
        _wait_until(
            lambda: victim.server.models["lm"].engine.heartbeat.age
            > fcfg.heartbeat_stale_s, what="heartbeat to go stale")
        router.probe_now()
        assert victim.health.state == EJECTED
        # release the hang: the engine loop resumes, heartbeat freshens
        faults.uninstall()
        _wait_until(
            lambda: victim.server.models["lm"].engine.heartbeat.age
            < fcfg.heartbeat_stale_s, what="heartbeat to freshen")
        router.probe_now()
        assert victim.health.state == HALF_OPEN
        # the victim reads as freer (no probed queue) → next dispatch
        # is its half-open trial; success reinstates it
        status, obj = _predict(router.port, "trial run", 4, timeout=30)
        assert status == 200
        _wait_until(lambda: victim.health.state == ACTIVE,
                    what="half-open trial to reinstate the replica")
        assert victim.health.snapshot()["recoveries"] == 1
    finally:
        faults.uninstall()
        shutdown(router)


def test_rolling_restart_under_load_zero_failed_requests(service):
    """ISSUE acceptance: a 3-replica rolling restart under sustained
    load finishes with zero failed requests — queued work is
    transplanted through the router, drain-window races are absorbed
    by the retry ladder, and every output stays token-identical."""
    fcfg = FleetConfig(dispatch_timeout_s=60.0, probe_interval_s=0.1,
                       retry_budget_burst=32.0, retry_budget_ratio=1.0)
    router, replicas = make_fleet(service, 3, fcfg)
    warm_all(replicas)
    router.start()
    prompt = "rolling restart survivor"
    want = greedy_reference(service, prompt, 5)
    results, failures = [], []
    stop = threading.Event()

    def client(wid):
        i = 0
        while not stop.is_set():
            try:
                status, obj = _predict(router.port, prompt, 5,
                                       timeout=60,
                                       rid=f"w{wid}-{i}")
                results.append((status, obj))
            except Exception as e:  # noqa: BLE001 - recorded, asserted
                failures.append(repr(e))
            i += 1

    workers = [threading.Thread(target=client, args=(w,))
               for w in range(4)]
    for t in workers:
        t.start()
    try:
        time.sleep(0.5)  # reach steady load first
        report = router.rolling_restart()
        time.sleep(0.5)  # and keep serving after the sweep
    finally:
        stop.set()
        for t in workers:
            t.join(timeout=60)
    try:
        assert report["completed"] is True
        assert failures == []  # ZERO transport/unhandled failures
        assert results, "load loop never completed a request"
        bad = [s for s, _ in results if s != 200]
        assert bad == []  # ZERO failed requests
        assert all(o["predictions"][0]["generated_text"] == want
                   for _, o in results)
        assert all(r.health.state == ACTIVE for r in replicas)
        assert all(r.server.models["lm"].engine.alive
                   for r in replicas)
        assert router.stats["rolling_restarts"] == 1
    finally:
        shutdown(router)


def test_transplant_moves_queued_request_to_peer(service):
    """The zero-drop mechanism in isolation: a request queued (never
    claimed) on a draining replica is re-admitted into a peer through
    the router, its waiter follows, and the output is token-identical."""
    fcfg = FleetConfig(probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg,
                                  engine_kw={"slots": 1})
    warm_all(replicas)
    eng0 = replicas[0].server.models["lm"].engine
    eng1 = replicas[1].server.models["lm"].engine
    try:
        # the one-shot reference compiles BEFORE the clock-sensitive
        # part (a fresh XLA compile takes tens of seconds on a cold
        # box — the queued request would drain while we wait on it)
        want = np.asarray(generate(
            CFG, service.params, jnp.asarray([[7, 8, 9]], jnp.int32),
            max_new_tokens=4, temperature=0.0, pad_token_id=0)
        )[0, 3:7].tolist()
        # occupy r0's only slot, slowly, then queue a second request
        faults.install(faults.FaultInjector(
            [FaultSpec("iteration", mode="slow", delay_s=0.05,
                       times=-1)]))
        long_req = eng0.submit(list(range(1, 9)), max_new_tokens=40,
                               temperature=0.0)
        queued = eng0.submit([7, 8, 9], max_new_tokens=4,
                             temperature=0.0)
        _wait_until(lambda: eng0.queue_depth() == 1,
                    what="second request to be queued")
        replicas[0].health.begin_drain()
        moved = router._transplant_from(replicas[0])
        assert moved == 1
        assert queued.engine is eng1  # the waiter follows its request
        assert queued.wait() == want  # token-identical on the peer
        assert router.stats["transplanted"] == 1
        assert len(long_req.wait()) == 40  # bystander unaffected
    finally:
        faults.uninstall()
        shutdown(router)


def test_fleet_dispatch_fault_contained_to_request(service):
    """fleet.dispatch containment: an injected raise at the dispatch
    site fails that one attempt (counted, retried within budget) —
    the replicas never see it and the next attempt succeeds."""
    fcfg = FleetConfig(dispatch_timeout_s=30.0, probe_interval_s=30.0)
    router, replicas = make_fleet(service, 2, fcfg)
    warm_all(replicas)
    router.start()
    try:
        want = greedy_reference(service, "contained", 4)
        faults.install(faults.FaultInjector(
            [FaultSpec("fleet.dispatch", at=1, times=1)]))
        status, obj = _predict(router.port, "contained", 4)
        assert status == 200
        assert obj["predictions"][0]["generated_text"] == want
        assert obj["fleet"]["retried_ok"] is True
        # both engines healthy: the fault never reached a replica
        assert all(r.server.models["lm"].engine.alive
                   for r in replicas)
    finally:
        faults.uninstall()
        shutdown(router)


def test_fleet_probe_hang_parks_only_the_prober(service):
    """fleet.probe containment: a hanging probe parks the prober
    thread only — dispatch keeps routing on last-known health, and
    the data plane never stalls."""
    fcfg = FleetConfig(dispatch_timeout_s=30.0, probe_interval_s=0.05)
    router, replicas = make_fleet(service, 2, fcfg)
    warm_all(replicas)
    router.start()
    try:
        faults.install(faults.FaultInjector(
            [FaultSpec("fleet.probe", mode="hang", times=-1,
                       delay_s=30.0)]))
        time.sleep(0.2)  # let the prober park in the hang
        t0 = time.monotonic()
        status, obj = _predict(router.port, "still serving", 4)
        assert status == 200
        assert time.monotonic() - t0 < 10.0  # never waited on the probe
        assert obj["fleet"]["dispatches"] == 1
    finally:
        faults.uninstall()
        shutdown(router)


def test_cancel_route_reaps_in_flight_request(service):
    """The new ``:cancel`` route (the hedge-loser path for remote
    replicas): cancelling by request id marks the in-flight request
    dead and the scheduler reaps it at its next pass."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    server = ModelServer([model], host="127.0.0.1", port=0)
    server.start()
    try:
        warm = model.engine.submit([1, 2, 3], max_new_tokens=2,
                                   temperature=0.0)
        warm.wait()
        faults.install(faults.FaultInjector(
            [FaultSpec("iteration", mode="slow", delay_s=0.05,
                       times=-1)]))
        got = {}

        def doomed():
            try:
                got["resp"] = _predict(server.port, "cancel me", 60,
                                       timeout=60, rid="doomed-1")
            except urllib.error.HTTPError as e:
                got["status"] = e.code

        t = threading.Thread(target=doomed)
        t.start()
        _wait_until(
            lambda: model.engine.request_phase("doomed-1") == "active",
            what="request to start decoding")
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/models/lm:cancel",
            data=json.dumps({"request_id": "doomed-1"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["cancelled"] is True
        t.join(timeout=30)
        assert got.get("status") == 500  # RequestCancelled surfaces
        assert model.engine.stats["cancelled"] >= 1
        assert model.engine.request_phase("doomed-1") is None
    finally:
        faults.uninstall()
        server.stop()
        model.stop()


def test_cancel_reaches_request_mid_admission(service):
    """cancel_request must see the claimed-but-not-yet-slotted window
    (a request wedged inside its prefill) — request_phase already
    calls it 'active', so a hedge loser caught there must be
    cancellable too."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=2, max_len=96))
    model.load()
    eng = model.engine
    try:
        eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0).wait()
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", mode="hang", at=1, times=1,
                       delay_s=60.0)]))
        req = eng.submit([4, 5, 6, 7], max_new_tokens=4,
                         temperature=0.0, request_id="adm-1")
        _wait_until(lambda: req.claimed and eng.queue_depth() == 0,
                    what="request claimed by the wedged admission")
        assert eng.request_phase("adm-1") == "active"
        assert eng.cancel_request("adm-1") is True
        faults.uninstall()  # prefill completes; the reaper evicts
        with pytest.raises(Exception, match="cancelled"):
            req.wait()
        assert eng.stats["cancelled"] >= 1
    finally:
        faults.uninstall()
        model.stop()


def test_engine_request_phase_lifecycle(service):
    """request_phase: queued → active → None (the hedging gate's
    exact vocabulary), including the multi-instance rid suffix."""
    model = ContinuousBatchingModel("lm", service,
                                    EngineConfig(slots=1, max_len=96))
    model.load()
    eng = model.engine
    try:
        warm = eng.submit([1, 2, 3], max_new_tokens=2, temperature=0.0)
        warm.wait()
        faults.install(faults.FaultInjector(
            [FaultSpec("iteration", mode="slow", delay_s=0.05,
                       times=-1)]))
        first = eng.submit(list(range(1, 9)), max_new_tokens=30,
                           temperature=0.0, request_id="rid-a-0")
        second = eng.submit([4, 5], max_new_tokens=2, temperature=0.0,
                            request_id="rid-b")
        _wait_until(lambda: eng.request_phase("rid-a") == "active",
                    what="first request active (suffix match)")
        assert eng.request_phase("rid-b") == "queued"
        assert eng.request_phase("rid-zzz") is None
        first.wait()
        second.wait()
        assert eng.request_phase("rid-b") is None
    finally:
        faults.uninstall()
        model.stop()
