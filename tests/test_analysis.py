"""kct-lint: rule self-tests on fixture snippets + whole-repo gate.

Every rule family gets a pair of fixtures — one that must fire, one
(the fixed form) that must stay quiet — so a rule can never silently
stop detecting its violation.  The whole-repo test is the actual gate:
the tree must be clean modulo the committed baseline, with no stale
suppressions.  All AST-based; the analysis package itself must import
without jax (verified by subprocess) so the gate runs on jax-free CI.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from kubernetes_cloud_tpu.analysis import (
    apply_baseline,
    load_baseline,
    run,
)
from kubernetes_cloud_tpu.analysis.cli import main as lint_main
from kubernetes_cloud_tpu.analysis.engine import BASELINE_FILE

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.lint]


# ---------------------------------------------------------------------------
# fixture scaffolding: a minimal repo that passes every rule
# ---------------------------------------------------------------------------

_ENG_OK = '''\
from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs.tracing import trace

_M = obs.counter("kct_x_total", "x", ("model",))


def admit(rid):
    faults.fire("model_fn")
    trace(rid, "queued", model="m")
'''

_BASE = {
    "kubernetes_cloud_tpu/__init__.py": "",
    "kubernetes_cloud_tpu/obs/__init__.py": "",
    "kubernetes_cloud_tpu/faults.py":
        'SITES = {"model_fn": "device call"}\n\n\n'
        'def fire(site):\n    return None\n',
    "kubernetes_cloud_tpu/obs/catalog.py":
        'METRIC_FAMILIES = {"kct_x_total": "x"}\n',
    "kubernetes_cloud_tpu/obs/tracing.py":
        'SPANS = ("queued", "complete")\n\n\n'
        'def trace(request_id, span, **fields):\n    pass\n',
    "kubernetes_cloud_tpu/serve/__init__.py": "",
    "kubernetes_cloud_tpu/serve/eng.py": _ENG_OK,
    "deploy/README.md": "sites: `model_fn`\nmetrics: `kct_x_total`\n",
}


def make_repo(tmp_path, extra=None, replace=None):
    files = dict(_BASE)
    files.update(replace or {})
    files.update(extra or {})
    for rel, content in files.items():
        if content is None:
            continue
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)
    return tmp_path


def rules_fired(root, select=None):
    return sorted({f.rule for f in run(root, select=select)})


def test_scaffold_is_clean(tmp_path):
    assert run(make_repo(tmp_path)) == []


# ---------------------------------------------------------------------------
# KCT-LOCK — lock discipline
# ---------------------------------------------------------------------------

_LOCKED_SLEEP = '''\
import threading
import time


class A:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            time.sleep(1.0)
'''


def test_lock_blocking_call_fires(tmp_path):
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    assert rules_fired(root, ["KCT-LOCK"]) == ["KCT-LOCK-001"]


def test_lock_fixed_form_quiet(tmp_path):
    fixed = _LOCKED_SLEEP.replace(
        "        with self._lock:\n            time.sleep(1.0)\n",
        "        with self._lock:\n            x = 1\n"
        "        time.sleep(1.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": fixed})
    assert rules_fired(root, ["KCT-LOCK"]) == []


@pytest.mark.parametrize("call,fires", [
    ("self._q.get()", True),            # unbounded queue get
    ("self._q.get(timeout=0.5)", False),  # bounded
    ("self._q.get_nowait()", False),
    ("self._t.join()", True),           # unbounded thread join
    ("self._t.join(timeout=1.0)", False),
    ('", ".join(parts)', False),        # str.join is not a thread join
    ("self._fh.write(data)", True),     # file I/O under lock
    ("open('/tmp/x')", True),
])
def test_lock_blocking_matrix(tmp_path, call, fires):
    src = ("import threading\n\n\nclass A:\n"
           "    def f(self, parts, data):\n"
           "        with self._lock:\n"
           f"            {call}\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    got = rules_fired(root, ["KCT-LOCK"])
    assert got == (["KCT-LOCK-001"] if fires else []), call


def test_lock_fault_point_fires(tmp_path):
    src = ("from kubernetes_cloud_tpu import faults\n\n\nclass A:\n"
           "    def f(self):\n"
           "        with self._qlock:\n"
           '            faults.fire("model_fn")\n')
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    assert rules_fired(root, ["KCT-LOCK"]) == ["KCT-LOCK-002"]


def test_lock_inline_suppression(tmp_path):
    src = _LOCKED_SLEEP.replace(
        "            time.sleep(1.0)",
        "            # kct-lint: ignore[KCT-LOCK-001] - test\n"
        "            time.sleep(1.0)")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    assert rules_fired(root, ["KCT-LOCK"]) == []


# ---------------------------------------------------------------------------
# KCT-JIT — trace purity + donation
# ---------------------------------------------------------------------------

def _jit_repo(tmp_path, body, header=""):
    src = (f"import jax\nimport numpy as np\nimport time\n{header}\n\n"
           "@jax.jit\n"
           "def f(x):\n"
           f"{body}"
           "    return x\n")
    return make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})


@pytest.mark.parametrize("body,rule", [
    ("    print(x)\n", "KCT-JIT-001"),
    ("    t = time.monotonic()\n", "KCT-JIT-001"),
    ("    r = np.random.default_rng(0)\n", "KCT-JIT-001"),
    ("    v = x.item()\n", "KCT-JIT-002"),
    ("    v = float(x)\n", "KCT-JIT-002"),
    ("    v = np.asarray(x)\n", "KCT-JIT-002"),
])
def test_jit_purity_fires(tmp_path, body, rule):
    assert rules_fired(_jit_repo(tmp_path, body), ["KCT-JIT"]) == [rule]


def test_jit_clean_body_quiet(tmp_path):
    root = _jit_repo(tmp_path, "    x = x * 2 + 1\n")
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_host_effect_outside_jit_quiet(tmp_path):
    src = ("import time\n\n\n"
           "def host_loop():\n"
           "    return time.monotonic()\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_call_form_resolves_local_def(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    print(x)\n"
           "    return x\n\n\n"
           "jitted = jax.jit(step)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-001"]


def test_jit_donated_reuse_fires(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    return x\n\n\n"
           "def runner(x):\n"
           "    j = jax.jit(step, donate_argnums=0)\n"
           "    y = j(x)\n"
           "    return x + y\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-003"]


def test_jit_donated_rebind_quiet(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    return x\n\n\n"
           "def runner(x):\n"
           "    j = jax.jit(step, donate_argnums=0)\n"
           "    x = j(x)\n"            # canonical donate-and-replace
           "    return x\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_argnum_out_of_range_fires(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x, y):\n"
           "    return x + y\n\n\n"
           "jitted = jax.jit(step, donate_argnums=5)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-004"]


def test_jit_static_params_not_traced(tmp_path):
    # float(cfg) on a static arg is host math by design — quiet
    src = ("import jax\n\n\n"
           "def step(cfg, x):\n"
           "    s = float(cfg)\n"
           "    return x * s\n\n\n"
           "jitted = jax.jit(step, static_argnums=0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


# ---------------------------------------------------------------------------
# KCT-REG — registry drift
# ---------------------------------------------------------------------------

def test_drift_unregistered_site_fires(tmp_path):
    bad = _ENG_OK.replace('faults.fire("model_fn")',
                          'faults.fire("model_fn")\n'
                          '    faults.fire("mystery_site")')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-001" in rules_fired(root, ["KCT-REG"])


def test_drift_unfired_site_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/faults.py":
            'SITES = {"model_fn": "x", "ghost_site": "never fired"}\n'
            '\n\ndef fire(site):\n    return None\n'})
    assert "KCT-REG-002" in rules_fired(root, ["KCT-REG"])


def test_drift_non_literal_site_fires(tmp_path):
    bad = _ENG_OK.replace('faults.fire("model_fn")',
                          'faults.fire("model_fn")\n'
                          '    faults.fire("site_" + rid)')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-003" in rules_fired(root, ["KCT-REG"])


def test_drift_undocumented_site_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "deploy/README.md": "metrics: `kct_x_total`\n"})  # no model_fn
    assert "KCT-REG-004" in rules_fired(root, ["KCT-REG"])


def test_drift_uncataloged_metric_fires(tmp_path):
    bad = _ENG_OK + '\n_M2 = obs.gauge("kct_rogue_depth", "y")\n'
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-005" in rules_fired(root, ["KCT-REG"])


def test_drift_undocumented_metric_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "deploy/README.md": "sites: `model_fn`\n"})  # no kct_x_total
    assert "KCT-REG-006" in rules_fired(root, ["KCT-REG"])


def test_drift_unregistered_catalog_entry_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/obs/catalog.py":
            'METRIC_FAMILIES = {"kct_x_total": "x", '
            '"kct_phantom_total": "never registered"}\n'})
    assert "KCT-REG-007" in rules_fired(root, ["KCT-REG"])


def test_drift_fstring_label_fires(tmp_path):
    bad = _ENG_OK + ('\n\ndef record(name):\n'
                     '    _M.labels(model=f"m-{name}").inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-009" in rules_fired(root, ["KCT-REG"])


def test_drift_fstring_label_via_kwargs_dict_fires(tmp_path):
    # the repo's dominant pattern is `.labels(**m)` over a dict literal
    # bound in the same scope — the rule must see through it
    bad = _ENG_OK + ('\n\ndef bind(name):\n'
                     '    m = {"model": f"m-{name}"}\n'
                     '    _M.labels(**m).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-009" in rules_fired(root, ["KCT-REG"])


def test_drift_bounded_kwargs_dict_quiet(tmp_path):
    ok = _ENG_OK + ('\n\ndef bind(self):\n'
                    '    m = {"model": self.name}\n'
                    '    _M.labels(**m).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": ok})
    assert rules_fired(root, ["KCT-REG"]) == []


def test_drift_bounded_label_quiet(tmp_path):
    ok = _ENG_OK + ('\n\ndef record(reason):\n'
                    '    _M.labels(model=reason).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": ok})
    assert rules_fired(root, ["KCT-REG"]) == []


def test_drift_off_vocabulary_span_fires(tmp_path):
    bad = _ENG_OK.replace('trace(rid, "queued", model="m")',
                          'trace(rid, "teleported", model="m")')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-010" in rules_fired(root, ["KCT-REG"])


# ---------------------------------------------------------------------------
# KCT-ERR — error taxonomy
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("body,rule", [
    ("try:\n    x()\nexcept:\n    pass\n", "KCT-ERR-001"),
    ("raise Exception('boom')\n", "KCT-ERR-002"),
    ("try:\n    x()\nexcept BaseException:\n    pass\n", "KCT-ERR-002"),
    ("try:\n    x()\nexcept Exception:\n    pass\n", "KCT-ERR-003"),
    ("raise RuntimeError('untyped')\n", "KCT-ERR-004"),
])
def test_taxonomy_fires(tmp_path, body, rule):
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/bad.py": body})
    assert rules_fired(root, ["KCT-ERR"]) == [rule]


def test_taxonomy_annotated_broad_except_quiet(tmp_path):
    src = ("try:\n    x()\n"
           "except Exception:  # noqa: BLE001 - best-effort teardown\n"
           "    pass\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/ok.py": src})
    assert rules_fired(root, ["KCT-ERR"]) == []


def test_taxonomy_typed_raise_quiet(tmp_path):
    src = ("from kubernetes_cloud_tpu.serve.errors import RetryableError"
           "\n\n\ndef f():\n    raise RetryableError('queue full')\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/ok.py": src})
    assert rules_fired(root, ["KCT-ERR"]) == []


def test_taxonomy_out_of_scope_quiet(tmp_path):
    # the taxonomy applies to serve/ and workflow/, not data/
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/data/bad.py":
            "raise RuntimeError('elsewhere')\n"})
    assert rules_fired(root, ["KCT-ERR"]) == []


# ---------------------------------------------------------------------------
# KCT-MAN — manifest rules
# ---------------------------------------------------------------------------

_GOOD_ISVC = """\
apiVersion: serving.kserve.io/v1beta1
kind: InferenceService
metadata:
  name: demo
  annotations:
    prometheus.io/scrape: "true"
    prometheus.io/port: "8080"
    prometheus.io/path: "/metrics"
spec:
  predictor:
    terminationGracePeriodSeconds: 60
    containers:
      - name: kserve-container
        image: x
        livenessProbe:
          httpGet: {path: /healthz, port: 8080}
        readinessProbe:
          httpGet: {path: /readyz, port: 8080}
        resources:
          requests: {cpu: "1", memory: 1Gi}
          limits: {google.com/tpu: 1}
    nodeSelector:
      cloud.google.com/gke-tpu-accelerator: tpu-v5-lite-podslice
      cloud.google.com/gke-tpu-topology: 2x2
"""


def test_manifest_good_isvc_quiet(tmp_path):
    root = make_repo(tmp_path, extra={
        "deploy/online-inference/demo/isvc.yaml": _GOOD_ISVC})
    assert rules_fired(root, ["KCT-MAN"]) == []


@pytest.mark.parametrize("mutate,rule", [
    (lambda t: t.replace("kind: InferenceService\n", ""), "KCT-MAN-001"),
    (lambda t: t.replace("google.com/tpu", "nvidia.com/gpu"),
     "KCT-MAN-002"),
    (lambda t: t.replace(
        "      cloud.google.com/gke-tpu-topology: 2x2\n", ""),
     "KCT-MAN-003"),
    (lambda t: t.replace("terminationGracePeriodSeconds: 60",
                         "terminationGracePeriodSeconds: 5"),
     "KCT-MAN-004"),
    (lambda t: t.replace("path: /readyz", "path: /healthz"),
     "KCT-MAN-004"),
    (lambda t: t.replace('    prometheus.io/scrape: "true"\n', ""),
     "KCT-MAN-005"),
    (lambda t: t.replace("          requests: {cpu: \"1\", memory: 1Gi}\n",
                         ""), "KCT-MAN-006"),
])
def test_manifest_violations_fire(tmp_path, mutate, rule):
    root = make_repo(tmp_path, extra={
        "deploy/online-inference/demo/isvc.yaml": mutate(_GOOD_ISVC)})
    assert rule in rules_fired(root, ["KCT-MAN"])


def test_manifest_unparseable_yaml_fires(tmp_path):
    root = make_repo(tmp_path, extra={
        "deploy/broken.yaml": "kind: [unclosed\n"})
    assert rules_fired(root, ["KCT-MAN"]) == ["KCT-MAN-001"]


# ---------------------------------------------------------------------------
# baseline mechanics: absorb, then go stale with a distinct exit code
# ---------------------------------------------------------------------------

def test_baseline_absorbs_then_goes_stale(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})

    # 1. violation present, no baseline: exit 1
    assert lint_main(["--root", str(root)]) == 1
    capsys.readouterr()

    # 2. write the baseline: the same run is now clean (exit 0)
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()

    # 3. fix the violation: the suppression is stale -> distinct exit
    #    code 2, and the stale entry is listed
    (root / "kubernetes_cloud_tpu/serve/locked.py").write_text(
        _LOCKED_SLEEP.replace("time.sleep(1.0)", "x = 1"))
    assert lint_main(["--root", str(root)]) == 2
    out = capsys.readouterr().out
    assert "stale suppression" in out
    assert "KCT-LOCK-001" in out

    # 4. deleting the entry restores a clean run
    (root / BASELINE_FILE).write_text(
        json.dumps({"version": 1, "suppressions": []}))
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()


def test_trailing_suppression_does_not_mask_next_line(tmp_path):
    # an end-of-line marker covers its own line ONLY; a second
    # violation on the next line must still be reported
    src = ("import threading\nimport time\n\n\nclass A:\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            time.sleep(1.0)  "
           "# kct-lint: ignore[KCT-LOCK-001] - x\n"
           "            time.sleep(2.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    findings = run(root, select=["KCT-LOCK"])
    assert len(findings) == 1 and findings[0].line == 9


def test_write_baseline_refuses_select(tmp_path, capsys):
    # --select sees a findings subset; writing it would truncate the
    # other families' committed suppressions
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    rc = lint_main(["--root", str(root), "--select", "KCT-MAN",
                    "--write-baseline"])
    assert rc == 3
    assert not (root / BASELINE_FILE).exists()


def test_corrupt_baseline_is_internal_error_not_findings(tmp_path,
                                                         capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    (root / BASELINE_FILE).write_text("<<<< merge conflict junk")
    assert lint_main(["--root", str(root)]) == 3
    assert "unreadable baseline" in capsys.readouterr().err


def test_select_ignores_other_families_baseline(tmp_path, capsys):
    # a KCT-MAN-scoped run must not report the committed KCT-ERR
    # baseline entries as stale (observed on the real repo)
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/bad.py": "raise Exception('x')\n"})
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(root), "--select", "KCT-MAN"]) == 0
    capsys.readouterr()


def test_baseline_is_a_multiset(tmp_path):
    # two identical findings, one baseline entry: one stays new
    src = _LOCKED_SLEEP.replace(
        "            time.sleep(1.0)\n",
        "            time.sleep(1.0)\n            time.sleep(1.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    findings = run(root, select=["KCT-LOCK"])
    assert len(findings) == 2
    entry = {"rule": findings[0].rule, "path": findings[0].path,
             "message": findings[0].message}
    new, stale = apply_baseline(findings, [entry])
    assert len(new) == 1 and not stale


def test_json_format_and_exit_codes(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/bad.py": "raise Exception('x')\n"})
    rc = lint_main(["--root", str(root), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["summary"]["new"] == 1
    f = out["findings"][0]
    assert f["rule"] == "KCT-ERR-002"
    assert f["path"] == "kubernetes_cloud_tpu/serve/bad.py"
    assert f["line"] == 1


def test_list_rules_covers_all_families(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for family in ("KCT-LOCK", "KCT-JIT", "KCT-REG", "KCT-ERR",
                   "KCT-MAN"):
        assert family in out, f"{family} missing from --list-rules"


# ---------------------------------------------------------------------------
# the actual gate: whole repo, committed baseline, no jax
# ---------------------------------------------------------------------------

def test_whole_repo_clean_modulo_baseline():
    findings = run(REPO_ROOT)
    entries = load_baseline(REPO_ROOT / BASELINE_FILE)
    new, stale = apply_baseline(findings, entries)
    assert not new, "new findings:\n" + "\n".join(
        f.format() for f in new)
    assert not stale, "stale baseline suppressions (delete them):\n" + \
        "\n".join(f"{e['rule']} {e['path']}: {e['message']}"
                  for e in stale)


def test_module_entry_point_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_cloud_tpu.analysis",
         "--format", "json", "--root", str(REPO_ROOT)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["new"] == 0
    assert payload["summary"]["stale"] == 0


def test_analysis_package_never_imports_jax():
    # the AST rules must run on jax-free boxes (and fast): importing
    # the package or running the engine must not pull jax in
    code = ("import sys\n"
            "from kubernetes_cloud_tpu.analysis import run\n"
            f"run({str(REPO_ROOT)!r}, select=['KCT-ERR'])\n"
            "assert 'jax' not in sys.modules, 'analysis imported jax'\n"
            "print('ok')\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.strip() == "ok"
