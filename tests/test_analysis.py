"""kct-lint: rule self-tests on fixture snippets + whole-repo gate.

Every rule family gets a pair of fixtures — one that must fire, one
(the fixed form) that must stay quiet — so a rule can never silently
stop detecting its violation.  The whole-repo test is the actual gate:
the tree must be clean modulo the committed baseline, with no stale
suppressions.  All AST-based; the analysis package itself must import
without jax (verified by subprocess) so the gate runs on jax-free CI.
"""

import json
import pathlib
import subprocess
import sys
import time

import pytest

from kubernetes_cloud_tpu.analysis import (
    apply_baseline,
    load_baseline,
    run,
)
from kubernetes_cloud_tpu.analysis.cli import main as lint_main
from kubernetes_cloud_tpu.analysis.engine import BASELINE_FILE

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.lint]


# ---------------------------------------------------------------------------
# fixture scaffolding: a minimal repo that passes every rule
# ---------------------------------------------------------------------------

_ENG_OK = '''\
from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs.tracing import trace

_M = obs.counter("kct_x_total", "x", ("model",))


def admit(rid):
    faults.fire("model_fn")
    trace(rid, "queued", model="m")
'''

_BASE = {
    "kubernetes_cloud_tpu/__init__.py": "",
    "kubernetes_cloud_tpu/obs/__init__.py": "",
    "kubernetes_cloud_tpu/faults.py":
        'SITES = {"model_fn": "device call"}\n\n\n'
        'def fire(site):\n    return None\n',
    "kubernetes_cloud_tpu/obs/catalog.py":
        'METRIC_FAMILIES = {"kct_x_total": "x"}\n',
    "kubernetes_cloud_tpu/obs/tracing.py":
        'SPANS = ("queued", "complete")\n\n\n'
        'def trace(request_id, span, **fields):\n    pass\n',
    "kubernetes_cloud_tpu/serve/__init__.py": "",
    "kubernetes_cloud_tpu/serve/eng.py": _ENG_OK,
    "deploy/README.md": "sites: `model_fn`\nmetrics: `kct_x_total`\n",
}


def make_repo(tmp_path, extra=None, replace=None):
    files = dict(_BASE)
    files.update(replace or {})
    files.update(extra or {})
    for rel, content in files.items():
        if content is None:
            continue
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)
    return tmp_path


def rules_fired(root, select=None):
    return sorted({f.rule for f in run(root, select=select)})


def test_scaffold_is_clean(tmp_path):
    assert run(make_repo(tmp_path)) == []


# ---------------------------------------------------------------------------
# KCT-LOCK — lock discipline
# ---------------------------------------------------------------------------

_LOCKED_SLEEP = '''\
import threading
import time


class A:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            time.sleep(1.0)
'''


def test_lock_blocking_call_fires(tmp_path):
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    assert rules_fired(root, ["KCT-LOCK"]) == ["KCT-LOCK-001"]


def test_lock_fixed_form_quiet(tmp_path):
    fixed = _LOCKED_SLEEP.replace(
        "        with self._lock:\n            time.sleep(1.0)\n",
        "        with self._lock:\n            x = 1\n"
        "        time.sleep(1.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": fixed})
    assert rules_fired(root, ["KCT-LOCK"]) == []


@pytest.mark.parametrize("call,fires", [
    ("self._q.get()", True),            # unbounded queue get
    ("self._q.get(timeout=0.5)", False),  # bounded
    ("self._q.get_nowait()", False),
    ("self._t.join()", True),           # unbounded thread join
    ("self._t.join(timeout=1.0)", False),
    ('", ".join(parts)', False),        # str.join is not a thread join
    ("self._fh.write(data)", True),     # file I/O under lock
    ("open('/tmp/x')", True),
])
def test_lock_blocking_matrix(tmp_path, call, fires):
    src = ("import threading\n\n\nclass A:\n"
           "    def f(self, parts, data):\n"
           "        with self._lock:\n"
           f"            {call}\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    got = rules_fired(root, ["KCT-LOCK"])
    assert got == (["KCT-LOCK-001"] if fires else []), call


def test_lock_fault_point_fires(tmp_path):
    src = ("from kubernetes_cloud_tpu import faults\n\n\nclass A:\n"
           "    def f(self):\n"
           "        with self._qlock:\n"
           '            faults.fire("model_fn")\n')
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    assert rules_fired(root, ["KCT-LOCK"]) == ["KCT-LOCK-002"]


def test_lock_inline_suppression(tmp_path):
    src = _LOCKED_SLEEP.replace(
        "            time.sleep(1.0)",
        "            # kct-lint: ignore[KCT-LOCK-001] - test\n"
        "            time.sleep(1.0)")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    assert rules_fired(root, ["KCT-LOCK"]) == []


# ---------------------------------------------------------------------------
# KCT-RACE — whole-program races, lock-order cycles, condition misuse
# ---------------------------------------------------------------------------

#: two thread roots, a lock discipline (2/3 accesses guarded), and one
#: plain write outside the guard
_RACE_WRITE = '''\
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def start(self):
        threading.Thread(target=self._a).start()
        threading.Thread(target=self._b).start()

    def _a(self):
        with self._lock:
            self._n = 1
        with self._lock:
            self._n = 2

    def _b(self):
        self._n = 3
'''

_RACE_GUARDED = _RACE_WRITE.replace(
    "    def _b(self):\n        self._n = 3\n",
    "    def _b(self):\n        with self._lock:\n"
    "            self._n = 3\n")


def _race_repo(tmp_path, src):
    return make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/shared.py": src})


def test_race_unguarded_write_fires(tmp_path):
    root = _race_repo(tmp_path, _RACE_WRITE)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-001"]


def test_race_guarded_twin_quiet(tmp_path):
    root = _race_repo(tmp_path, _RACE_GUARDED)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_single_root_quiet(tmp_path):
    # same unguarded write, but only ONE thread ever runs the code:
    # no second root, no race
    single = _RACE_WRITE.replace(
        "        threading.Thread(target=self._b).start()\n", "")
    root = _race_repo(tmp_path, single)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_rmw_fires(tmp_path):
    src = _RACE_WRITE.replace("        self._n = 3\n",
                              "        self._n += 1\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-002"]


def test_race_check_then_set_is_rmw(tmp_path):
    src = _RACE_WRITE.replace(
        "        self._n = 3\n",
        "        if self._n == 0:\n            self._n = 3\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-002"]


def test_race_rmw_guarded_twin_quiet(tmp_path):
    src = _RACE_WRITE.replace(
        "    def _b(self):\n        self._n = 3\n",
        "    def _b(self):\n        with self._lock:\n"
        "            self._n += 1\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_helper_called_under_lock_is_guarded(tmp_path):
    # interprocedural guard context: the write happens in a helper
    # only ever called with the lock held, so it counts as guarded
    src = _RACE_WRITE.replace(
        "    def _b(self):\n        self._n = 3\n",
        "    def _b(self):\n        with self._lock:\n"
        "            self._set()\n\n"
        "    def _set(self):\n        self._n = 3\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_init_writes_exempt(tmp_path):
    # __init__ runs before the object is published to other threads
    src = _RACE_GUARDED.replace(
        "        self._n = 0\n",
        "        self._n = 0\n        self._n = 1\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


_RACE_LEAK = '''\
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def start(self):
        threading.Thread(target=self._a).start()
        threading.Thread(target=self._b).start()

    def _a(self):
        with self._lock:
            self._items.append(1)
        with self._lock:
            self._items.append(2)

    def _b(self):
        with self._lock:
            return self._items
'''


def test_race_leak_fires(tmp_path):
    root = _race_repo(tmp_path, _RACE_LEAK)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-003"]


def test_race_leak_copy_quiet(tmp_path):
    src = _RACE_LEAK.replace("return self._items",
                             "return list(self._items)")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


_RACE_ABBA = '''\
import threading


class C:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def start(self):
        threading.Thread(target=self.one).start()
        threading.Thread(target=self.two).start()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def two(self):
        with self._b_lock:
            self.helper()

    def helper(self):
        with self._a_lock:
            pass
'''


def test_race_abba_cycle_fires(tmp_path):
    # the B->A edge goes through a method call: only the whole-program
    # lock-order graph sees it
    root = _race_repo(tmp_path, _RACE_ABBA)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-004"]


def test_race_consistent_order_quiet(tmp_path):
    src = _RACE_ABBA.replace(
        "    def two(self):\n        with self._b_lock:\n"
        "            self.helper()\n",
        "    def two(self):\n        with self._a_lock:\n"
        "            self.helper()\n").replace(
        "    def helper(self):\n        with self._a_lock:\n",
        "    def helper(self):\n        with self._b_lock:\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


_RACE_WAIT = '''\
import threading


class C:
    def __init__(self):
        self._cond = threading.Condition()
        self._ready = False

    def start(self):
        threading.Thread(target=self.consume).start()

    def consume(self):
        with self._cond:
            self._cond.wait(timeout=1.0)
'''


def test_race_wait_without_loop_fires(tmp_path):
    root = _race_repo(tmp_path, _RACE_WAIT)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-005"]


def test_race_wait_in_predicate_loop_quiet(tmp_path):
    src = _RACE_WAIT.replace(
        "        with self._cond:\n"
        "            self._cond.wait(timeout=1.0)\n",
        "        with self._cond:\n"
        "            while not self._ready:\n"
        "                self._cond.wait(timeout=1.0)\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_wait_for_quiet(tmp_path):
    src = _RACE_WAIT.replace(
        "            self._cond.wait(timeout=1.0)\n",
        "            self._cond.wait_for(lambda: self._ready,\n"
        "                                timeout=1.0)\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


_RACE_NOTIFY = '''\
import threading


class C:
    def __init__(self):
        self._cond = threading.Condition()

    def start(self):
        threading.Thread(target=self.produce).start()

    def produce(self):
        self._cond.notify_all()
'''


def test_race_notify_outside_lock_fires(tmp_path):
    root = _race_repo(tmp_path, _RACE_NOTIFY)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-006"]


def test_race_notify_under_lock_quiet(tmp_path):
    src = _RACE_NOTIFY.replace(
        "    def produce(self):\n        self._cond.notify_all()\n",
        "    def produce(self):\n        with self._cond:\n"
        "            self._cond.notify_all()\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_notify_in_helper_with_locked_callers_quiet(tmp_path):
    # the notify lives in a helper whose every call site holds the
    # condition — the interprocedural context keeps it quiet
    src = _RACE_NOTIFY.replace(
        "    def produce(self):\n        self._cond.notify_all()\n",
        "    def produce(self):\n        with self._cond:\n"
        "            self._wake()\n\n"
        "    def _wake(self):\n        self._cond.notify_all()\n")
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == []


def test_race_timer_and_executor_roots(tmp_path):
    # a Timer callback and a pool.submit callable are thread roots; an
    # executor root is concurrent with ITSELF, so one root suffices
    src = '''\
import threading
from concurrent.futures import ThreadPoolExecutor


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def start(self):
        pool = ThreadPoolExecutor(4)
        for _ in range(4):
            pool.submit(self._work)

    def _work(self):
        with self._lock:
            self._n = 1
        with self._lock:
            self._n = 2
        self._n = 3
'''
    root = _race_repo(tmp_path, src)
    assert rules_fired(root, ["KCT-RACE"]) == ["KCT-RACE-001"]


def test_thread_root_discovery_whole_repo():
    # the model must find the serve plane's known daemon loops — the
    # continuous scheduler, autoscaler loop, supervisor, prober,
    # spawner — plus the HTTP entry; and the activator's capacity
    # notification must be reachable from the spawner root
    from kubernetes_cloud_tpu.analysis.engine import Repo

    model = Repo(REPO_ROOT).program()
    names = {r.name for r in model.roots}
    for expected in (
            "serve/continuous.py:ContinuousBatchingEngine._loop",
            "serve/autoscaler.py:Autoscaler._run",
            "serve/supervisor.py:ServingSupervisor._loop",
            "serve/fleet.py:FleetRouter._probe_loop",
            "serve/autoscaler.py:ElasticFleet._spawn",
            "serve/server.py:ModelServer.handle"):
        assert any(n.endswith(expected) for n in names), \
            f"thread root {expected} not discovered; got {sorted(names)}"
    spawn = next(i for i, r in enumerate(model.roots)
                 if r.name.endswith("ElasticFleet._spawn"))
    notify = [fkey for fkey in model.functions
              if fkey[1].endswith("Activator.notify_capacity")]
    assert notify and any(
        spawn in model.roots_reaching.get(fkey, set())
        for fkey in notify), \
        "Activator.notify_capacity not reachable from the spawner root"


# ---------------------------------------------------------------------------
# KCT-JIT — trace purity + donation
# ---------------------------------------------------------------------------

def _jit_repo(tmp_path, body, header=""):
    src = (f"import jax\nimport numpy as np\nimport time\n{header}\n\n"
           "@jax.jit\n"
           "def f(x):\n"
           f"{body}"
           "    return x\n")
    return make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})


@pytest.mark.parametrize("body,rule", [
    ("    print(x)\n", "KCT-JIT-001"),
    ("    t = time.monotonic()\n", "KCT-JIT-001"),
    ("    r = np.random.default_rng(0)\n", "KCT-JIT-001"),
    ("    v = x.item()\n", "KCT-JIT-002"),
    ("    v = float(x)\n", "KCT-JIT-002"),
    ("    v = np.asarray(x)\n", "KCT-JIT-002"),
])
def test_jit_purity_fires(tmp_path, body, rule):
    assert rules_fired(_jit_repo(tmp_path, body), ["KCT-JIT"]) == [rule]


def test_jit_clean_body_quiet(tmp_path):
    root = _jit_repo(tmp_path, "    x = x * 2 + 1\n")
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_host_effect_outside_jit_quiet(tmp_path):
    src = ("import time\n\n\n"
           "def host_loop():\n"
           "    return time.monotonic()\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_call_form_resolves_local_def(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    print(x)\n"
           "    return x\n\n\n"
           "jitted = jax.jit(step)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-001"]


def test_jit_donated_reuse_fires(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    return x\n\n\n"
           "def runner(x):\n"
           "    j = jax.jit(step, donate_argnums=0)\n"
           "    y = j(x)\n"
           "    return x + y\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-003"]


def test_jit_donated_rebind_quiet(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x):\n"
           "    return x\n\n\n"
           "def runner(x):\n"
           "    j = jax.jit(step, donate_argnums=0)\n"
           "    x = j(x)\n"            # canonical donate-and-replace
           "    return x\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


def test_jit_argnum_out_of_range_fires(tmp_path):
    src = ("import jax\n\n\n"
           "def step(x, y):\n"
           "    return x + y\n\n\n"
           "jitted = jax.jit(step, donate_argnums=5)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == ["KCT-JIT-004"]


def test_jit_static_params_not_traced(tmp_path):
    # float(cfg) on a static arg is host math by design — quiet
    src = ("import jax\n\n\n"
           "def step(cfg, x):\n"
           "    s = float(cfg)\n"
           "    return x * s\n\n\n"
           "jitted = jax.jit(step, static_argnums=0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/models.py": src})
    assert rules_fired(root, ["KCT-JIT"]) == []


# ---------------------------------------------------------------------------
# KCT-REG — registry drift
# ---------------------------------------------------------------------------

def test_drift_unregistered_site_fires(tmp_path):
    bad = _ENG_OK.replace('faults.fire("model_fn")',
                          'faults.fire("model_fn")\n'
                          '    faults.fire("mystery_site")')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-001" in rules_fired(root, ["KCT-REG"])


def test_drift_unfired_site_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/faults.py":
            'SITES = {"model_fn": "x", "ghost_site": "never fired"}\n'
            '\n\ndef fire(site):\n    return None\n'})
    assert "KCT-REG-002" in rules_fired(root, ["KCT-REG"])


def test_drift_non_literal_site_fires(tmp_path):
    bad = _ENG_OK.replace('faults.fire("model_fn")',
                          'faults.fire("model_fn")\n'
                          '    faults.fire("site_" + rid)')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-003" in rules_fired(root, ["KCT-REG"])


def test_drift_undocumented_site_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "deploy/README.md": "metrics: `kct_x_total`\n"})  # no model_fn
    assert "KCT-REG-004" in rules_fired(root, ["KCT-REG"])


def test_drift_uncataloged_metric_fires(tmp_path):
    bad = _ENG_OK + '\n_M2 = obs.gauge("kct_rogue_depth", "y")\n'
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-005" in rules_fired(root, ["KCT-REG"])


def test_drift_undocumented_metric_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "deploy/README.md": "sites: `model_fn`\n"})  # no kct_x_total
    assert "KCT-REG-006" in rules_fired(root, ["KCT-REG"])


def test_drift_unregistered_catalog_entry_fires(tmp_path):
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/obs/catalog.py":
            'METRIC_FAMILIES = {"kct_x_total": "x", '
            '"kct_phantom_total": "never registered"}\n'})
    assert "KCT-REG-007" in rules_fired(root, ["KCT-REG"])


def test_drift_fstring_label_fires(tmp_path):
    bad = _ENG_OK + ('\n\ndef record(name):\n'
                     '    _M.labels(model=f"m-{name}").inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-009" in rules_fired(root, ["KCT-REG"])


def test_drift_fstring_label_via_kwargs_dict_fires(tmp_path):
    # the repo's dominant pattern is `.labels(**m)` over a dict literal
    # bound in the same scope — the rule must see through it
    bad = _ENG_OK + ('\n\ndef bind(name):\n'
                     '    m = {"model": f"m-{name}"}\n'
                     '    _M.labels(**m).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-009" in rules_fired(root, ["KCT-REG"])


def test_drift_bounded_kwargs_dict_quiet(tmp_path):
    ok = _ENG_OK + ('\n\ndef bind(self):\n'
                    '    m = {"model": self.name}\n'
                    '    _M.labels(**m).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": ok})
    assert rules_fired(root, ["KCT-REG"]) == []


def test_drift_bounded_label_quiet(tmp_path):
    ok = _ENG_OK + ('\n\ndef record(reason):\n'
                    '    _M.labels(model=reason).inc()\n')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": ok})
    assert rules_fired(root, ["KCT-REG"]) == []


def test_drift_off_vocabulary_span_fires(tmp_path):
    bad = _ENG_OK.replace('trace(rid, "queued", model="m")',
                          'trace(rid, "teleported", model="m")')
    root = make_repo(tmp_path, replace={
        "kubernetes_cloud_tpu/serve/eng.py": bad})
    assert "KCT-REG-010" in rules_fired(root, ["KCT-REG"])


# ---------------------------------------------------------------------------
# KCT-ERR — error taxonomy
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("body,rule", [
    ("try:\n    x()\nexcept:\n    pass\n", "KCT-ERR-001"),
    ("raise Exception('boom')\n", "KCT-ERR-002"),
    ("try:\n    x()\nexcept BaseException:\n    pass\n", "KCT-ERR-002"),
    ("try:\n    x()\nexcept Exception:\n    pass\n", "KCT-ERR-003"),
    ("raise RuntimeError('untyped')\n", "KCT-ERR-004"),
])
def test_taxonomy_fires(tmp_path, body, rule):
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/bad.py": body})
    assert rules_fired(root, ["KCT-ERR"]) == [rule]


def test_taxonomy_annotated_broad_except_quiet(tmp_path):
    src = ("try:\n    x()\n"
           "except Exception:  # noqa: BLE001 - best-effort teardown\n"
           "    pass\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/ok.py": src})
    assert rules_fired(root, ["KCT-ERR"]) == []


def test_taxonomy_typed_raise_quiet(tmp_path):
    src = ("from kubernetes_cloud_tpu.serve.errors import RetryableError"
           "\n\n\ndef f():\n    raise RetryableError('queue full')\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/ok.py": src})
    assert rules_fired(root, ["KCT-ERR"]) == []


def test_taxonomy_out_of_scope_quiet(tmp_path):
    # the taxonomy applies to serve/ and workflow/, not data/
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/data/bad.py":
            "raise RuntimeError('elsewhere')\n"})
    assert rules_fired(root, ["KCT-ERR"]) == []


# ---------------------------------------------------------------------------
# KCT-MAN — manifest rules
# ---------------------------------------------------------------------------

_GOOD_ISVC = """\
apiVersion: serving.kserve.io/v1beta1
kind: InferenceService
metadata:
  name: demo
  annotations:
    prometheus.io/scrape: "true"
    prometheus.io/port: "8080"
    prometheus.io/path: "/metrics"
spec:
  predictor:
    terminationGracePeriodSeconds: 60
    containers:
      - name: kserve-container
        image: x
        livenessProbe:
          httpGet: {path: /healthz, port: 8080}
        readinessProbe:
          httpGet: {path: /readyz, port: 8080}
        resources:
          requests: {cpu: "1", memory: 1Gi}
          limits: {google.com/tpu: 1}
    nodeSelector:
      cloud.google.com/gke-tpu-accelerator: tpu-v5-lite-podslice
      cloud.google.com/gke-tpu-topology: 2x2
"""


def test_manifest_good_isvc_quiet(tmp_path):
    root = make_repo(tmp_path, extra={
        "deploy/online-inference/demo/isvc.yaml": _GOOD_ISVC})
    assert rules_fired(root, ["KCT-MAN"]) == []


@pytest.mark.parametrize("mutate,rule", [
    (lambda t: t.replace("kind: InferenceService\n", ""), "KCT-MAN-001"),
    (lambda t: t.replace("google.com/tpu", "nvidia.com/gpu"),
     "KCT-MAN-002"),
    (lambda t: t.replace(
        "      cloud.google.com/gke-tpu-topology: 2x2\n", ""),
     "KCT-MAN-003"),
    (lambda t: t.replace("terminationGracePeriodSeconds: 60",
                         "terminationGracePeriodSeconds: 5"),
     "KCT-MAN-004"),
    (lambda t: t.replace("path: /readyz", "path: /healthz"),
     "KCT-MAN-004"),
    (lambda t: t.replace('    prometheus.io/scrape: "true"\n', ""),
     "KCT-MAN-005"),
    (lambda t: t.replace("          requests: {cpu: \"1\", memory: 1Gi}\n",
                         ""), "KCT-MAN-006"),
])
def test_manifest_violations_fire(tmp_path, mutate, rule):
    root = make_repo(tmp_path, extra={
        "deploy/online-inference/demo/isvc.yaml": mutate(_GOOD_ISVC)})
    assert rule in rules_fired(root, ["KCT-MAN"])


def test_manifest_unparseable_yaml_fires(tmp_path):
    root = make_repo(tmp_path, extra={
        "deploy/broken.yaml": "kind: [unclosed\n"})
    assert rules_fired(root, ["KCT-MAN"]) == ["KCT-MAN-001"]


# ---------------------------------------------------------------------------
# baseline mechanics: absorb, then go stale with a distinct exit code
# ---------------------------------------------------------------------------

def test_baseline_absorbs_then_goes_stale(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})

    # 1. violation present, no baseline: exit 1
    assert lint_main(["--root", str(root)]) == 1
    capsys.readouterr()

    # 2. write the baseline: the same run is now clean (exit 0)
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()

    # 3. fix the violation: the suppression is stale -> distinct exit
    #    code 2, and the stale entry is listed
    (root / "kubernetes_cloud_tpu/serve/locked.py").write_text(
        _LOCKED_SLEEP.replace("time.sleep(1.0)", "x = 1"))
    assert lint_main(["--root", str(root)]) == 2
    out = capsys.readouterr().out
    assert "stale suppression" in out
    assert "KCT-LOCK-001" in out

    # 4. deleting the entry restores a clean run
    (root / BASELINE_FILE).write_text(
        json.dumps({"version": 1, "suppressions": []}))
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()


def test_trailing_suppression_does_not_mask_next_line(tmp_path):
    # an end-of-line marker covers its own line ONLY; a second
    # violation on the next line must still be reported
    src = ("import threading\nimport time\n\n\nclass A:\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            time.sleep(1.0)  "
           "# kct-lint: ignore[KCT-LOCK-001] - x\n"
           "            time.sleep(2.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    findings = run(root, select=["KCT-LOCK"])
    assert len(findings) == 1 and findings[0].line == 9


def test_write_baseline_refuses_select(tmp_path, capsys):
    # --select sees a findings subset; writing it would truncate the
    # other families' committed suppressions
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    rc = lint_main(["--root", str(root), "--select", "KCT-MAN",
                    "--write-baseline"])
    assert rc == 3
    assert not (root / BASELINE_FILE).exists()


def test_corrupt_baseline_is_internal_error_not_findings(tmp_path,
                                                         capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    (root / BASELINE_FILE).write_text("<<<< merge conflict junk")
    assert lint_main(["--root", str(root)]) == 3
    assert "unreadable baseline" in capsys.readouterr().err


def test_select_ignores_other_families_baseline(tmp_path, capsys):
    # a KCT-MAN-scoped run must not report the committed KCT-ERR
    # baseline entries as stale (observed on the real repo)
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/bad.py": "raise Exception('x')\n"})
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(root), "--select", "KCT-MAN"]) == 0
    capsys.readouterr()


def test_baseline_is_a_multiset(tmp_path):
    # two identical findings, one baseline entry: one stays new
    src = _LOCKED_SLEEP.replace(
        "            time.sleep(1.0)\n",
        "            time.sleep(1.0)\n            time.sleep(1.0)\n")
    root = make_repo(tmp_path, extra={
        "kubernetes_cloud_tpu/serve/locked.py": src})
    findings = run(root, select=["KCT-LOCK"])
    assert len(findings) == 2
    entry = {"rule": findings[0].rule, "path": findings[0].path,
             "message": findings[0].message}
    new, stale = apply_baseline(findings, [entry])
    assert len(new) == 1 and not stale


def test_json_format_and_exit_codes(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/bad.py": "raise Exception('x')\n"})
    rc = lint_main(["--root", str(root), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["summary"]["new"] == 1
    f = out["findings"][0]
    assert f["rule"] == "KCT-ERR-002"
    assert f["path"] == "kubernetes_cloud_tpu/serve/bad.py"
    assert f["line"] == 1


def test_list_rules_covers_all_families(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for family in ("KCT-LOCK", "KCT-RACE", "KCT-JIT", "KCT-REG",
                   "KCT-ERR", "KCT-MAN"):
        assert family in out, f"{family} missing from --list-rules"


# ---------------------------------------------------------------------------
# sarif output, --prune-baseline, --changed
# ---------------------------------------------------------------------------

def test_sarif_format_shape(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    rc = lint_main(["--root", str(root), "--format", "sarif"])
    assert rc == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    driver = log["runs"][0]["tool"]["driver"]
    assert driver["name"] == "kct-lint"
    ids = {r["id"] for r in driver["rules"]}
    assert {"KCT-LOCK-001", "KCT-RACE-001", "KCT-RACE-004"} <= ids
    results = log["runs"][0]["results"]
    assert len(results) == 1
    r = results[0]
    assert r["ruleId"] == "KCT-LOCK-001"
    assert r["level"] == "error"
    loc = r["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == \
        "kubernetes_cloud_tpu/serve/locked.py"
    assert loc["region"]["startLine"] == 11


def test_sarif_clean_run_has_no_results(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    rc = lint_main(["--root", str(root), "--format", "sarif"])
    assert rc == 0
    log = json.loads(capsys.readouterr().out)
    assert log["runs"][0]["results"] == []


def test_prune_baseline_roundtrips_to_zero(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    # fix the violation: the entry is stale (exit 2)
    (root / "kubernetes_cloud_tpu/serve/locked.py").write_text(
        _LOCKED_SLEEP.replace("time.sleep(1.0)", "x = 1"))
    assert lint_main(["--root", str(root)]) == 2
    capsys.readouterr()
    # prune rewrites the file and the run is clean in one pass...
    assert lint_main(["--root", str(root), "--prune-baseline"]) == 0
    assert "pruned 1 stale suppression" in capsys.readouterr().out
    # ...and the pruned file round-trips to exit 0 with no flags
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()
    data = json.loads((root / BASELINE_FILE).read_text())
    assert data["suppressions"] == []


def test_prune_baseline_keeps_live_entries(tmp_path, capsys):
    # two baselined findings, one fixed: prune drops exactly the stale
    # entry and keeps the live one
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP,
        "kubernetes_cloud_tpu/serve/bad.py": "raise Exception('x')\n"})
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    (root / "kubernetes_cloud_tpu/serve/locked.py").write_text(
        _LOCKED_SLEEP.replace("time.sleep(1.0)", "x = 1"))
    assert lint_main(["--root", str(root), "--prune-baseline"]) == 0
    capsys.readouterr()
    data = json.loads((root / BASELINE_FILE).read_text())
    assert [e["rule"] for e in data["suppressions"]] == ["KCT-ERR-002"]
    assert lint_main(["--root", str(root)]) == 0
    capsys.readouterr()


def test_prune_baseline_refuses_scoped_runs(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    for extra in (["--select", "KCT-RACE"], ["--changed"],
                  ["--no-baseline"], ["--write-baseline"]):
        rc = lint_main(["--root", str(root), "--prune-baseline",
                        *extra])
        assert rc == 3, extra
        assert "prune-baseline" in capsys.readouterr().err


def _git(root, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@example.com", "-c", "user.name=t",
         *args],
        cwd=root, check=True, capture_output=True, text=True)


def test_changed_scopes_findings_to_the_diff(tmp_path, capsys):
    # a committed violation is invisible to --changed HEAD; a freshly
    # added one is reported — pre-commit only talks about your diff
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-q", "-m", "base")
    assert lint_main(["--root", str(root), "--changed"]) == 0
    capsys.readouterr()
    (root / "kubernetes_cloud_tpu/serve/fresh.py").write_text(
        _LOCKED_SLEEP)
    rc = lint_main(["--root", str(root), "--changed", "--format",
                    "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["path"] for f in out["findings"]] == \
        ["kubernetes_cloud_tpu/serve/fresh.py"]


def test_changed_ignores_unchanged_files_stale_entries(tmp_path,
                                                       capsys):
    # baseline entries for files OUTSIDE the diff must not be reported
    # stale by a diff-scoped run
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n",
        "kubernetes_cloud_tpu/serve/locked.py": _LOCKED_SLEEP})
    assert lint_main(["--root", str(root), "--write-baseline"]) == 0
    capsys.readouterr()
    (root / "kubernetes_cloud_tpu/serve/locked.py").write_text(
        _LOCKED_SLEEP.replace("time.sleep(1.0)", "x = 1"))
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-q", "-m", "base")
    # nothing changed vs HEAD: the (globally stale) entry is out of
    # scope, so the scoped run exits clean
    assert lint_main(["--root", str(root), "--changed"]) == 0
    capsys.readouterr()


def test_changed_bad_ref_is_usage_error(tmp_path, capsys):
    root = make_repo(tmp_path, extra={
        "pyproject.toml": "[project]\nname = 'fixture'\n"})
    _git(root, "init", "-q")
    rc = lint_main(["--root", str(root), "--changed",
                    "not-a-ref-at-all"])
    assert rc == 3
    assert "--changed" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the actual gate: whole repo, committed baseline, no jax
# ---------------------------------------------------------------------------

#: quick-lane ceiling for the whole-repo run INCLUDING the program-
#: model build (measured ~4 s on the CI box; generous for slow ones)
_GATE_BUDGET_S = 60.0


def test_whole_repo_clean_modulo_baseline():
    t0 = time.monotonic()
    findings = run(REPO_ROOT)
    elapsed = time.monotonic() - t0
    entries = load_baseline(REPO_ROOT / BASELINE_FILE)
    new, stale = apply_baseline(findings, entries)
    assert not new, "new findings:\n" + "\n".join(
        f.format() for f in new)
    assert not stale, "stale baseline suppressions (delete them):\n" + \
        "\n".join(f"{e['rule']} {e['path']}: {e['message']}"
                  for e in stale)
    assert elapsed < _GATE_BUDGET_S, (
        f"whole-repo lint took {elapsed:.1f}s — over the quick-lane "
        f"budget of {_GATE_BUDGET_S:.0f}s; the program model must "
        "stay cheap")


def test_module_entry_point_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_cloud_tpu.analysis",
         "--format", "json", "--root", str(REPO_ROOT)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["new"] == 0
    assert payload["summary"]["stale"] == 0


def test_analysis_package_never_imports_jax():
    # the AST rules must run on jax-free boxes (and fast): importing
    # the package or running the engine must not pull jax in
    code = ("import sys\n"
            "from kubernetes_cloud_tpu.analysis import run\n"
            f"run({str(REPO_ROOT)!r}, select=['KCT-ERR'])\n"
            "assert 'jax' not in sys.modules, 'analysis imported jax'\n"
            "print('ok')\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.strip() == "ok"
