"""Pipeline parallelism vs. non-pipelined forward on the CPU mesh."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models.causal_lm import (
    PRESETS,
    forward,
    init_params,
    loss_fn,
)
from kubernetes_cloud_tpu.parallel.pipeline import (
    pipeline_forward,
    pipeline_loss_fn,
)
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)


def _ids(cfg, b=8, s=32, key=0):
    return jax.random.randint(jax.random.key(key), (b, s), 0,
                              cfg.vocab_size, dtype=jnp.int32)


@pytest.fixture
def stage_mesh(devices8):
    # 2 stages x data=2 x fsdp=2: pipeline composed with sharded-dp.
    return build_mesh(MeshSpec(data=2, fsdp=2, stage=2), devices=devices8)


def test_pipeline_forward_matches_dense(devices8):
    cfg = PRESETS["test-tiny"]  # 2 layers -> 2 stages x 1 layer
    mesh = build_mesh(MeshSpec(data=1, stage=2, fsdp=4), devices=devices8)
    params = jax.jit(init_params, static_argnums=0)(cfg, jax.random.key(0))
    ids = _ids(cfg)
    mask = jnp.ones_like(ids).at[:, 28:].set(0)

    want = forward(cfg, params, ids, attention_mask=mask)
    got = jax.jit(functools.partial(
        pipeline_forward, cfg, mesh=mesh, n_microbatches=4))(
        params, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_train_step(stage_mesh):
    cfg = PRESETS["test-tiny"]
    tc = TrainConfig(warmup_steps=2, total_steps=10)
    state = init_train_state(cfg, tc, jax.random.key(0), stage_mesh)
    batch = {"input_ids": _ids(cfg, b=8, s=32, key=1),
             "attention_mask": jnp.ones((8, 32), jnp.int32)}
    dense_loss, _ = loss_fn(cfg, state["params"], batch)

    sharded = shard_batch(batch, stage_mesh)
    step = jax.jit(make_train_step(
        cfg, tc, loss=functools.partial(pipeline_loss_fn, n_microbatches=4),
        mesh=stage_mesh))
    state2, metrics = step(state, sharded)
    np.testing.assert_allclose(float(metrics["loss"]), float(dense_loss),
                               rtol=2e-4)
    assert int(state2["step"]) == 1
    assert np.isfinite(float(metrics["grad_norm"]))


def test_pipeline_grad_matches_dense(devices8):
    """Gradients through the pipeline schedule equal the dense gradients."""
    cfg = PRESETS["test-tiny"]
    mesh = build_mesh(MeshSpec(data=1, stage=2, fsdp=1, model=1,
                               seq=1), devices=devices8[:2])
    params = jax.jit(init_params, static_argnums=0)(cfg, jax.random.key(0))
    batch = {"input_ids": _ids(cfg, b=4, s=32, key=2)}

    g_dense = jax.grad(
        lambda p: loss_fn(cfg, p, batch)[0])(params)
    g_pipe = jax.jit(jax.grad(
        lambda p: pipeline_loss_fn(cfg, p, batch, mesh,
                                   n_microbatches=2)[0]))(params)
    flat_d = jax.tree_util.tree_leaves(g_dense)
    flat_p = jax.tree_util.tree_leaves(g_pipe)
    # Both paths compute in bfloat16; the pipeline adds fp32<->bf16 boundary
    # casts, so agreement is bounded by bf16 rounding (~1%), not fp32 eps.
    for a, b in zip(flat_d, flat_p):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.abs(a).max()
        assert np.abs(a - b).max() <= 0.03 * scale + 1e-5


def test_pipeline_composed_with_seq_parallel(devices8):
    """stage=2 x seq=2 x data=2: ring attention inside pipelined stages."""
    cfg = dataclasses.replace(PRESETS["test-tiny"], attn_impl="ring")
    mesh = build_mesh(MeshSpec(data=2, stage=2, seq=2), devices=devices8)
    tc = TrainConfig(warmup_steps=2, total_steps=10)
    state = init_train_state(cfg, tc, jax.random.key(0), mesh)
    batch = {"input_ids": _ids(cfg, b=8, s=32, key=3),
             "attention_mask": jnp.ones((8, 32), jnp.int32)}
    dense_loss, _ = loss_fn(PRESETS["test-tiny"], state["params"], batch)

    sharded = shard_batch(batch, mesh)
    step = jax.jit(make_train_step(
        cfg, tc, loss=functools.partial(pipeline_loss_fn, n_microbatches=2),
        mesh=mesh))
    _, metrics = step(state, sharded)
    np.testing.assert_allclose(float(metrics["loss"]), float(dense_loss),
                               rtol=3e-4)


def test_pipeline_composed_with_moe(devices8):
    """stage=2 x expert=2 x data=2: MoE aux loss threads through the
    microbatch schedule and matches the non-pipelined path."""
    cfg = dataclasses.replace(PRESETS["test-tiny"], moe_experts=4)
    mesh = build_mesh(MeshSpec(data=2, stage=2, expert=2), devices=devices8)
    tc = TrainConfig(warmup_steps=2, total_steps=10)
    state = init_train_state(cfg, tc, jax.random.key(0), mesh)
    # Padded mask: padding tokens must not route into experts or claim
    # capacity on either path (token_mask plumbing through the schedule).
    mask = jnp.ones((8, 32), jnp.int32).at[:, 28:].set(0)
    batch = {"input_ids": _ids(cfg, b=8, s=32, key=5),
             "attention_mask": mask}
    dense_loss, dense_metrics = loss_fn(cfg, state["params"], batch)

    sharded = shard_batch(batch, mesh)
    step = jax.jit(make_train_step(
        cfg, tc, loss=functools.partial(pipeline_loss_fn, n_microbatches=4),
        mesh=mesh))
    state2, metrics = step(state, sharded)
    # Routing groups are per-microbatch under the pipeline, so the aux term
    # (weighted 0.01 into the loss) differs at the margin, not exactly.
    np.testing.assert_allclose(float(metrics["loss"]), float(dense_loss),
                               rtol=2e-3)
    np.testing.assert_allclose(float(metrics["aux_loss"]),
                               float(dense_metrics["aux_loss"]), rtol=2e-2)
    assert int(state2["step"]) == 1
    assert np.isfinite(float(metrics["grad_norm"]))


def test_pipeline_rejects_bad_shapes(devices8):
    cfg = PRESETS["test-tiny"]
    mesh = build_mesh(MeshSpec(data=4, stage=2), devices=devices8)
    params = {}
    with pytest.raises(ValueError, match="microbatch"):
        pipeline_forward(cfg, params, jnp.ones((3, 8), jnp.int32),
                         mesh=mesh, n_microbatches=2)


def test_pipeline_chunked_loss_matches_dense(devices8):
    """loss_chunk_size must take effect through the pipelined path too."""
    import dataclasses
    import functools

    from kubernetes_cloud_tpu.models.causal_lm import PRESETS, init_params
    from kubernetes_cloud_tpu.parallel.sharding import shard_params
    from kubernetes_cloud_tpu.utils.compat import _HAS_AXIS_NAMES

    if not _HAS_AXIS_NAMES:
        pytest.skip("shard_map lacks axis_names= (partial-manual mode) "
                    "on this jax; the pipelined chunked-loss path needs it")

    mesh = build_mesh(MeshSpec(stage=2, data=2), devices=devices8[:4])
    cfg = PRESETS["test-tiny"]
    params = init_params(cfg, jax.random.key(0))
    params = shard_params(params, mesh)
    ids = jax.random.randint(jax.random.key(1), (4, 32), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    batch = shard_batch({"input_ids": ids,
                         "attention_mask": jnp.ones((4, 32), jnp.int32)},
                        mesh)
    dense = jax.jit(functools.partial(
        pipeline_loss_fn, cfg, mesh=mesh, n_microbatches=2))(
        params, batch)[0]
    ccfg = dataclasses.replace(cfg, loss_chunk_size=8)
    chunked = jax.jit(functools.partial(
        pipeline_loss_fn, ccfg, mesh=mesh, n_microbatches=2))(
        params, batch)[0]
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5)
