"""deploy/ manifest library sanity.

The YAML surface is the L5/L6 public interface (SURVEY.md §1).  The
structural per-document assertions this file used to hardcode — GPU
leftovers, TPU accelerator+topology selector pairing, InferenceService
probe/drain wiring, Prometheus scrape annotations, resource requests —
are now declarative rules in ``kubernetes_cloud_tpu/analysis`` (the
KCT-MAN family), run here through the same engine ``kct-lint`` uses, so
a new manifest is checked the day it lands.  What stays hardcoded below
is the repo-specific topology: the flagship workflow's 1:1 parameter
surface, step DAG, event-binding references, JobSet symmetry, and the
``.ready.txt`` sentinel protocol.
"""

import pathlib

import pytest
import yaml

from kubernetes_cloud_tpu.analysis import run as lint_run

ROOT = pathlib.Path(__file__).resolve().parent.parent
DEPLOY = ROOT / "deploy"
YAMLS = sorted(DEPLOY.rglob("*.yaml"))


def _docs(path):
    # Argo template braces are valid YAML scalars; sprig expressions with
    # `{{=...}}` inside quoted strings parse fine with safe_load.
    return [d for d in yaml.safe_load_all(path.read_text()) if d is not None]


def test_manifests_exist():
    assert len(YAMLS) >= 15


# ---------------------------------------------------------------------------
# generalized structural rules: one engine run, asserted clean
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_manifest_rules_clean():
    """deploy/**/*.yaml passes every declarative KCT-MAN rule (parse +
    kind/apiVersion, no GPU leftovers, TPU selector pairing, probe &
    drain contract, scrape annotations, resource requests)."""
    findings = lint_run(ROOT, select=["KCT-MAN"])
    assert not findings, "\n".join(f.format() for f in findings)


@pytest.mark.lint
def test_manifest_rules_cover_the_serving_catalog():
    """The probe/scrape rules are only meaningful if they actually see
    the catalog: count the online-inference InferenceServices the
    engine walked (≥ 8 — the whole serving catalog)."""
    seen = 0
    for path in (DEPLOY / "online-inference").rglob("*.yaml"):
        for doc in _docs(path):
            if doc.get("kind") == "InferenceService":
                seen += 1
    assert seen >= 8


# ---------------------------------------------------------------------------
# repo-specific topology (not generalizable into rules)
# ---------------------------------------------------------------------------

def test_finetune_workflow_parameter_surface():
    wf = _docs(DEPLOY / "finetuner-workflow" / "finetune-workflow.yaml")[0]
    params = {p["name"] for p in wf["spec"]["arguments"]["parameters"]}
    # The reference's user-facing config surface (SURVEY.md §5.6) ports 1:1.
    expected = {
        "run_name", "pvc", "model", "dataset", "tensorizer_uri",
        "retokenize", "sanitize", "tokenizer", "reorder", "no_shuffle",
        "sampling", "eot_token", "pad_token", "boundary_token",
        "boundary_index", "context", "prompt_file", "prompt_every",
        "prompt_tokens", "prompt_samples", "top_k", "top_p", "temperature",
        "repetition_penalty", "warmup_ratio", "batch_size", "force_fp16",
        "batch_size_divisor", "random_seed", "learn_rate", "epochs",
        "gradients", "zero_stage", "save_steps", "no_resume", "logs",
        "wandb_key", "project_id", "run_inference", "inference_only",
        "download_dataset",
    }
    missing = expected - params
    assert not missing, f"missing workflow params: {sorted(missing)}"


def test_finetune_workflow_step_dag():
    wf = _docs(DEPLOY / "finetuner-workflow" / "finetune-workflow.yaml")[0]
    main = next(t for t in wf["spec"]["templates"] if t["name"] == "main")
    step_names = [s[0]["name"] for s in main["steps"]]
    assert step_names == [
        "check-model", "model-downloader", "dataset-downloader",
        "tokenizer", "finetuner", "inference-service",
    ]
    # Every non-main template retries or is a resource apply
    # (reference retryStrategy on all steps, SURVEY.md §5.3).
    for t in wf["spec"]["templates"]:
        if t["name"] in ("main", "model-inference-service"):
            continue
        assert "retryStrategy" in t, t["name"]


def test_event_bindings_reference_their_templates():
    for wf_dir, binding, template in [
        ("sd-finetuner-workflow", "sd-finetune-workflow-event-binding.yaml",
         "sd-finetune-template"),
        ("sd-dreambooth-workflow", "db-workflow-event-binding.yaml",
         "db-finetune-template"),
    ]:
        doc = _docs(DEPLOY / wf_dir / binding)[0]
        assert doc["kind"] == "WorkflowEventBinding"
        assert doc["spec"]["submit"]["workflowTemplateRef"]["name"] == template
        tmpl_files = [p for p in (DEPLOY / wf_dir).glob("*.yaml")
                      if p.name != binding]
        names = {d["metadata"].get("name")
                 for f in tmpl_files for d in _docs(f)}
        assert template in names


def test_jobsets_are_symmetric():
    """JobSet workers: no launcher/worker asymmetry (SURVEY.md §7 hard part
    5) — a single replicatedJob where every host runs the same command."""
    for path in (DEPLOY / "jobset").glob("*jobset.yaml"):
        for doc in _docs(path):
            if doc["kind"] != "JobSet":
                continue
            jobs = doc["spec"]["replicatedJobs"]
            assert len(jobs) == 1, f"{path}: expected symmetric single job"
            spec = jobs[0]["template"]["spec"]
            assert spec["parallelism"] == spec["completions"]


def test_ready_sentinel_protocol_present():
    text = (DEPLOY / "online-inference" / "bloom-176b" /
            "01-download-job.yaml").read_text()
    assert ".ready.txt" in text
