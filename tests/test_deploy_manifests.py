"""deploy/ manifest library sanity.

The YAML surface is the L5/L6 public interface (SURVEY.md §1); these tests
keep it loadable and structurally consistent: every file parses, every TPU
workload pairs a google.com/tpu limit with gke-tpu nodeSelectors, and the
flagship workflow keeps the reference's 1:1 parameter surface
(finetuner-workflow/finetune-workflow.yaml:8-199).
"""

import pathlib
import re

import pytest
import yaml

DEPLOY = pathlib.Path(__file__).resolve().parent.parent / "deploy"
YAMLS = sorted(DEPLOY.rglob("*.yaml"))


def _docs(path):
    # Argo template braces are valid YAML scalars; sprig expressions with
    # `{{=...}}` inside quoted strings parse fine with safe_load.
    return [d for d in yaml.safe_load_all(path.read_text()) if d is not None]


def test_manifests_exist():
    assert len(YAMLS) >= 15


@pytest.mark.parametrize("path", YAMLS, ids=lambda p: str(p.relative_to(DEPLOY)))
def test_manifest_parses(path):
    docs = _docs(path)
    assert docs, f"{path} has no documents"
    for doc in docs:
        assert "kind" in doc and "apiVersion" in doc


def test_no_gpu_resources_anywhere():
    """TPU-native means no nvidia.com/gpu or CUDA scheduling leftovers."""
    for path in YAMLS:
        text = "\n".join(
            line for line in path.read_text().splitlines()
            if not line.lstrip().startswith("#"))
        assert "nvidia.com/gpu" not in text, path
        assert "rdma/ib" not in text, path


def test_tpu_workloads_pair_limits_with_selectors():
    for path in YAMLS:
        text = path.read_text()
        if "google.com/tpu" in text:
            assert "gke-tpu-accelerator" in text, (
                f"{path}: TPU limit without accelerator nodeSelector")


def test_finetune_workflow_parameter_surface():
    wf = _docs(DEPLOY / "finetuner-workflow" / "finetune-workflow.yaml")[0]
    params = {p["name"] for p in wf["spec"]["arguments"]["parameters"]}
    # The reference's user-facing config surface (SURVEY.md §5.6) ports 1:1.
    expected = {
        "run_name", "pvc", "model", "dataset", "tensorizer_uri",
        "retokenize", "sanitize", "tokenizer", "reorder", "no_shuffle",
        "sampling", "eot_token", "pad_token", "boundary_token",
        "boundary_index", "context", "prompt_file", "prompt_every",
        "prompt_tokens", "prompt_samples", "top_k", "top_p", "temperature",
        "repetition_penalty", "warmup_ratio", "batch_size", "force_fp16",
        "batch_size_divisor", "random_seed", "learn_rate", "epochs",
        "gradients", "zero_stage", "save_steps", "no_resume", "logs",
        "wandb_key", "project_id", "run_inference", "inference_only",
        "download_dataset",
    }
    missing = expected - params
    assert not missing, f"missing workflow params: {sorted(missing)}"


def test_finetune_workflow_step_dag():
    wf = _docs(DEPLOY / "finetuner-workflow" / "finetune-workflow.yaml")[0]
    main = next(t for t in wf["spec"]["templates"] if t["name"] == "main")
    step_names = [s[0]["name"] for s in main["steps"]]
    assert step_names == [
        "check-model", "model-downloader", "dataset-downloader",
        "tokenizer", "finetuner", "inference-service",
    ]
    # Every non-main template retries or is a resource apply
    # (reference retryStrategy on all steps, SURVEY.md §5.3).
    for t in wf["spec"]["templates"]:
        if t["name"] in ("main", "model-inference-service"):
            continue
        assert "retryStrategy" in t, t["name"]


def test_event_bindings_reference_their_templates():
    for wf_dir, binding, template in [
        ("sd-finetuner-workflow", "sd-finetune-workflow-event-binding.yaml",
         "sd-finetune-template"),
        ("sd-dreambooth-workflow", "db-workflow-event-binding.yaml",
         "db-finetune-template"),
    ]:
        doc = _docs(DEPLOY / wf_dir / binding)[0]
        assert doc["kind"] == "WorkflowEventBinding"
        assert doc["spec"]["submit"]["workflowTemplateRef"]["name"] == template
        tmpl_files = [p for p in (DEPLOY / wf_dir).glob("*.yaml")
                      if p.name != binding]
        names = {d["metadata"].get("name")
                 for f in tmpl_files for d in _docs(f)}
        assert template in names


def test_jobsets_are_symmetric():
    """JobSet workers: no launcher/worker asymmetry (SURVEY.md §7 hard part
    5) — a single replicatedJob where every host runs the same command."""
    for path in (DEPLOY / "jobset").glob("*jobset.yaml"):
        for doc in _docs(path):
            if doc["kind"] != "JobSet":
                continue
            jobs = doc["spec"]["replicatedJobs"]
            assert len(jobs) == 1, f"{path}: expected symmetric single job"
            spec = jobs[0]["template"]["spec"]
            assert spec["parallelism"] == spec["completions"]


def test_inference_services_wire_probes_and_drain():
    """The KServe/Knative probe-and-drain contract (serve/server.py):
    every online-inference InferenceService probes liveness at /healthz
    (process alive, unconditional) and readiness at /readyz (the honest
    serving state), and budgets terminationGracePeriodSeconds for the
    SIGTERM drain."""
    for path in (DEPLOY / "online-inference").rglob("*.yaml"):
        for doc in _docs(path):
            if doc.get("kind") != "InferenceService":
                continue
            pred = doc["spec"]["predictor"]
            assert pred.get("terminationGracePeriodSeconds", 0) >= 60, (
                f"{path}: no drain budget")
            ctr = pred["containers"][0]
            live = ctr.get("livenessProbe", {}).get("httpGet", {})
            ready = ctr.get("readinessProbe", {}).get("httpGet", {})
            assert live.get("path") == "/healthz", (
                f"{path}: livenessProbe must target /healthz")
            assert ready.get("path") == "/readyz", (
                f"{path}: readinessProbe must target /readyz")


def test_inference_services_opt_into_prometheus_scraping():
    """The metrics plane (kubernetes_cloud_tpu/obs + GET /metrics on
    both serving front-ends) is only useful if the cluster Prometheus
    actually pulls it: every online-inference InferenceService must
    carry the scrape annotations, pointed at the serving port's
    /metrics."""
    seen = 0
    for path in (DEPLOY / "online-inference").rglob("*.yaml"):
        for doc in _docs(path):
            if doc.get("kind") != "InferenceService":
                continue
            seen += 1
            ann = doc["metadata"].get("annotations") or {}
            assert ann.get("prometheus.io/scrape") == "true", (
                f"{path}: missing prometheus.io/scrape annotation")
            assert ann.get("prometheus.io/port") == "8080", (
                f"{path}: prometheus.io/port must be the serving port")
            assert ann.get("prometheus.io/path") == "/metrics", (
                f"{path}: prometheus.io/path must be /metrics")
    assert seen >= 8  # the whole serving catalog is covered


def test_ready_sentinel_protocol_present():
    text = (DEPLOY / "online-inference" / "bloom-176b" /
            "01-download-job.yaml").read_text()
    assert ".ready.txt" in text
