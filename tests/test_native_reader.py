"""Native C++ batch reader: bit-parity with the Python mmap path and the
ctypes surface (``csrc/batch_reader``)."""

import numpy as np
import pytest

from kubernetes_cloud_tpu.data import native_reader
from kubernetes_cloud_tpu.data.tokenized import TokenizedDataset

CONTEXT = 64
PAD = 7


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("tokens") / "data.tokens"
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 500, size=(32, CONTEXT)).astype(np.uint16)
    # rows with trailing pad runs and one mid-row pad
    rows[3, -10:] = PAD
    rows[5, -1:] = PAD
    rows[9, 20] = PAD  # mid-row pad must stay visible
    rows[9, -4:] = PAD
    rows.tofile(path)
    return str(path)


def test_available_and_build():
    assert native_reader.available()  # g++ is in the image


def test_parity_with_python_path(token_file):
    ds = TokenizedDataset(token_file, CONTEXT, pad_token=PAD)
    assert ds._native is not None
    idx = np.array([3, 5, 9, 0, 31])
    native = ds.gather(idx)
    ids_py = np.asarray(ds.tokens[idx], np.int32)
    mask_py = ds.mask_for(ids_py)
    np.testing.assert_array_equal(native["input_ids"], ids_py)
    np.testing.assert_array_equal(native["attention_mask"], mask_py)
    # spot-check mask semantics
    assert native["attention_mask"][0, -10:].sum() == 0  # trailing run
    assert native["attention_mask"][2, 20] == 1  # mid-row pad visible
    assert native["attention_mask"][2, -4:].sum() == 0


def test_no_pad_token_all_ones(token_file):
    r = native_reader.NativeTokenReader(token_file, CONTEXT, None)
    out = r.gather(np.arange(4))
    assert out["attention_mask"].min() == 1
    r.close()


def test_oob_row_raises(token_file):
    r = native_reader.NativeTokenReader(token_file, CONTEXT, PAD)
    with pytest.raises(IndexError):
        r.gather(np.array([0, 99]))
    r.close()


def test_prefetch_noop_safe(token_file):
    r = native_reader.NativeTokenReader(token_file, CONTEXT, PAD)
    r.prefetch(np.array([0, 5, 31, 100]))  # oob rows silently skipped
    r.close()


def test_bad_file_rejected(tmp_path):
    bad = tmp_path / "bad.tokens"
    bad.write_bytes(b"\x01\x02\x03")  # not a whole number of rows
    with pytest.raises(OSError):
        native_reader.NativeTokenReader(str(bad), CONTEXT, PAD)


def test_slice_gather_offsets(token_file):
    ds = TokenizedDataset(token_file, CONTEXT, pad_token=PAD)
    lo, hi = ds.split(0.5)
    got = hi.gather(np.array([0, 1]))
    want = ds.gather(np.array([16, 17]))
    np.testing.assert_array_equal(got["input_ids"], want["input_ids"])
    with pytest.raises(IndexError):
        hi.gather(np.array([16]))
