"""Elastic-fleet chaos: the autoscaler's live acceptance scenarios
over real engines.

* scale-from-zero through the activator: a request that arrives at an
  EMPTY fleet parks on the activator, the poked control loop spawns a
  replica, and the held request replays onto it — one 200, token-
  identical to one-shot greedy ``generate``, nothing dropped and
  nothing re-prefilled behind the client's back;
* a flash crowd forces a scale-up while a fault kills a replica
  mid-burst — the retry ladder absorbs the crash, the control loop
  replaces the capacity, and ZERO client requests fail;
* prefill and decode pools are independent: scaling one role never
  touches the other role's replicas.

Same determinism stance as ``test_fleet_chaos``: engines are warmed
before faults arm, and the assertions are about counters and health
states, not wall-clock racing.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.serve.autoscaler import (
    AutoscalerConfig,
    ElasticFleet,
    RolePolicy,
)
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingModel,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.fleet import (
    ACTIVE,
    FleetConfig,
    FleetRouter,
    LocalReplica,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.serve.server import ModelServer

pytestmark = [pytest.mark.chaos, pytest.mark.fleet]

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def service(params):
    svc = CausalLMService("lm", CFG, params=params, dtype=jnp.float32)
    svc.load()
    return svc


def make_factory(service, fcfg, engine_kw=None):
    """An ElasticFleet factory: each spawn gets its OWN engine over
    the shared weights, UNLOADED — the spawner thread pays ``load()``
    so the measured cold start is honest."""
    kw = {"slots": 2, "max_len": 96}
    kw.update(engine_kw or {})

    def factory(role, rid):
        model = ContinuousBatchingModel("lm", service,
                                        EngineConfig(**kw))
        server = ModelServer([model], host="127.0.0.1", port=0)
        return LocalReplica(rid, server, fcfg)

    return factory


def make_seeded_replica(service, rid, fcfg, engine_kw=None):
    """A pre-warmed replica for fleets that do NOT start from zero."""
    kw = {"slots": 2, "max_len": 96}
    kw.update(engine_kw or {})
    model = ContinuousBatchingModel("lm", service, EngineConfig(**kw))
    model.load()
    replica = LocalReplica(rid, ModelServer([model], host="127.0.0.1",
                                            port=0), fcfg)
    model.engine.submit([1, 2, 3], max_new_tokens=2,
                        temperature=0.0).wait()
    return replica


def teardown(fleet, router):
    fleet.stop()
    router.shutdown()


def _predict(port, prompt, max_new, timeout=60, rid=None):
    payload = {"instances": [prompt],
               "parameters": {"max_new_tokens": max_new,
                              "temperature": 0.0}}
    if rid:
        payload["request_id"] = rid
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/lm:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def greedy_reference(service, prompt, n):
    opts = {"MAX_NEW_TOKENS": n, "TEMPERATURE": 0.0, "TOP_K": 0,
            "TOP_P": 1.0, "SEED": 0, "ECHO_PROMPT": False}
    return service.generate_texts([prompt], opts)[0]


def _wait_until(cond, timeout=30.0, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def test_scale_from_zero_activator_holds_and_replays(service):
    """ISSUE acceptance: a request arriving at an EMPTY fleet is held
    by the activator (never 503d), the poke wakes the control loop,
    a replica cold-starts, and the held request replays onto it —
    the client sees one 200, token-identical to greedy generate."""
    fcfg = FleetConfig(dispatch_timeout_s=60.0, probe_interval_s=0.1)
    router = FleetRouter([], fcfg, host="127.0.0.1", port=0,
                         allow_empty=True)
    cfg = AutoscalerConfig(
        tick_s=0.05, stable_window_s=0.5, panic_window_s=0.2,
        scale_down_delay_s=60.0, cooldown_s=0.05, prewarm=False,
        scale_to_zero_grace_s=60.0, cold_start_prior_s=10.0,
        roles={"colocated": RolePolicy(min_replicas=0, max_replicas=2,
                                       target_concurrency=2.0)})
    fleet = ElasticFleet(router, make_factory(service, fcfg), cfg)
    router.start()
    fleet.start()
    try:
        want = greedy_reference(service, "wake the fleet", 5)
        status, obj = _predict(router.port, "wake the fleet", 5,
                               timeout=90)
        assert status == 200
        assert obj["predictions"][0]["generated_text"] == want
        # the hold-and-replay path really ran: held once, replayed
        # once, and NOTHING was 503d or silently re-prefilled
        assert fleet.activator.stats["held"] >= 1
        assert fleet.activator.stats["replayed"] >= 1
        assert fleet.activator.stats["timeouts"] == 0
        assert router.stats["unplaceable"] == 0
        assert router.stats["activator_held"] >= 1
        assert router.stats["activator_replayed"] >= 1
        # exactly the capacity asked for, probed healthy and ACTIVE
        assert len(router.replicas) == 1
        assert router.replicas[0].health.state == ACTIVE
        # the measured cold start replaced the configured prior
        measured = fleet.autoscaler.cold_start_s("colocated")
        assert measured != pytest.approx(cfg.cold_start_prior_s)
        assert 0.0 < measured < 60.0
    finally:
        teardown(fleet, router)


def test_flash_crowd_scale_up_with_replica_killed_mid_burst(service):
    """ISSUE acceptance: a flash crowd drives concurrency over target
    → the control loop spawns capacity; a fault kills an engine in
    the middle of the scale-up — retries absorb the crash, the loop
    replaces the lost replica, and ZERO client requests fail."""
    fcfg = FleetConfig(dispatch_timeout_s=60.0, probe_interval_s=0.1,
                       retry_budget_burst=64.0, retry_budget_ratio=1.0)
    seed = make_seeded_replica(service, "r0", fcfg)
    router = FleetRouter([seed], fcfg, host="127.0.0.1", port=0)
    cfg = AutoscalerConfig(
        tick_s=0.05, stable_window_s=0.4, panic_window_s=0.2,
        panic_threshold=1.5, scale_down_delay_s=60.0, cooldown_s=0.05,
        prewarm=False,
        roles={"colocated": RolePolicy(min_replicas=1, max_replicas=3,
                                       target_concurrency=1.0)})
    fleet = ElasticFleet(router, make_factory(service, fcfg), cfg)
    router.start()
    fleet.start()
    prompt = "flash crowd burst"
    want = greedy_reference(service, prompt, 5)
    results, failures = [], []
    stop = threading.Event()

    def client(wid):
        i = 0
        while not stop.is_set():
            try:
                status, obj = _predict(router.port, prompt, 5,
                                       timeout=60, rid=f"w{wid}-{i}")
                results.append((status, obj))
            except Exception as e:  # noqa: BLE001 - recorded, asserted
                failures.append(repr(e))
            i += 1

    workers = [threading.Thread(target=client, args=(w,))
               for w in range(5)]
    for t in workers:
        t.start()
    try:
        # let the burst register and the scale-up begin...
        _wait_until(lambda: fleet.autoscaler.stats["scale_ups"] >= 1,
                    what="the flash crowd to trigger a scale-up")
        # ...then kill the next decoding engine mid-scale-up
        faults.install(faults.FaultInjector(
            [FaultSpec("decode_step", at=1, times=1)]))
        _wait_until(lambda: any(
            not r.server.models["lm"].engine.alive
            for r in router.replicas), what="the fault to kill an engine")
        faults.uninstall()  # spawned replacements must come up clean
        # the loop must refill the pool: >= 2 ACTIVE live engines
        _wait_until(lambda: sum(
            1 for r in router.replicas
            if r.health.state == ACTIVE
            and r.server.models["lm"].engine.alive) >= 2,
            timeout=60,
            what="the control loop to replace the killed replica")
        time.sleep(0.5)  # keep serving on the rebuilt pool
    finally:
        stop.set()
        for t in workers:
            t.join(timeout=60)
    try:
        assert failures == []  # ZERO transport/unhandled failures
        assert results, "load loop never completed a request"
        assert [s for s, _ in results if s != 200] == []
        assert all(o["predictions"][0]["generated_text"] == want
                   for _, o in results)
        assert fleet.autoscaler.stats["scale_ups"] >= 1
        assert router.stats["unplaceable"] == 0
    finally:
        teardown(fleet, router)


def test_supervised_replica_wired_to_control_loop(service):
    """A supervised model's restarts change ready capacity mid-tick:
    ElasticFleet points the supervisor's capacity hook at the control
    loop, so a restart/circuit-open wakes it immediately."""
    from kubernetes_cloud_tpu.serve.supervisor import ServingSupervisor

    fcfg = FleetConfig(probe_interval_s=30.0)
    rep = make_seeded_replica(service, "s0", fcfg)
    sup = ServingSupervisor()
    sup.watch(rep.server.models["lm"])
    router = FleetRouter([rep], fcfg, host="127.0.0.1", port=0)
    cfg = AutoscalerConfig(
        roles={"colocated": RolePolicy(min_replicas=1, max_replicas=2,
                                       target_concurrency=4.0)})
    fleet = ElasticFleet(router, make_factory(service, fcfg), cfg)
    try:
        assert sup.on_capacity_change == fleet.autoscaler.kick
        fleet.autoscaler._kick.clear()
        sup._notify_capacity_change()
        assert fleet.autoscaler._kick.is_set()
    finally:
        teardown(fleet, router)


def test_prefill_and_decode_pools_scale_independently(service):
    """Role isolation: scaling the prefill pool spawns/drains ONLY
    prefill replicas — the decode pool's membership never moves."""
    fcfg = FleetConfig(dispatch_timeout_s=60.0, probe_interval_s=30.0)
    pre = make_seeded_replica(service, "pre0", fcfg)
    dec = make_seeded_replica(service, "dec0", fcfg)
    pre.health.role = "prefill"
    dec.health.role = "decode"
    router = FleetRouter([pre, dec], fcfg, host="127.0.0.1", port=0)
    cfg = AutoscalerConfig(
        tick_s=0.05, stable_window_s=0.5, panic_window_s=0.2,
        scale_down_delay_s=60.0, cooldown_s=0.05, prewarm=False,
        roles={"prefill": RolePolicy(min_replicas=1, max_replicas=4,
                                     target_concurrency=2.0),
               "decode": RolePolicy(min_replicas=1, max_replicas=4,
                                    target_concurrency=2.0)})
    fleet = ElasticFleet(router, make_factory(service, fcfg), cfg)
    try:
        assert fleet.signals("prefill").ready == 1
        assert fleet.signals("decode").ready == 1

        # scale prefill up: the spawn is role-tagged and joins the
        # prefill pool; decode membership is untouched
        assert fleet.scale_up("prefill", 1) == 1
        _wait_until(lambda: fleet.signals("prefill").ready == 2,
                    what="prefill spawn to probe healthy")
        assert fleet.signals("decode").ready == 1
        spawned = [r for r in router.replicas
                   if r.id not in ("pre0", "dec0")]
        assert len(spawned) == 1
        assert spawned[0].health.role == "prefill"

        # scale prefill back down: the drain victim is a prefill
        # replica; the decode replica never drains
        assert fleet.scale_down("prefill", 1) == 1
        _wait_until(lambda: len(router.replicas) == 2,
                    what="prefill drain to complete")
        assert fleet.signals("prefill").ready == 1
        assert fleet.signals("decode").ready == 1
        assert dec.health.state == ACTIVE

        # asking decode for a drain never victimizes prefill
        assert fleet.scale_down("decode", 1) == 1
        _wait_until(lambda: len(router.replicas) == 1,
                    what="decode drain to complete")
        assert router.replicas[0].health.role == "prefill"
    finally:
        teardown(fleet, router)
