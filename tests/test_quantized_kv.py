"""Int8 quantized paged-KV arena + fused decode kernel: correctness lock.

The quantization tentpole relaxes the repo's token-identity discipline
to a MEASURED logit-error budget, so this file locks exactly that
contract:

1. arena round-trip quantization error stays within the half-step
   bound the per-(page, head) scale implies;
2. the dequant-in-kernel Mosaic path (interpret mode), the jnp gather
   fallback, and the fused gather+attention+projection kernel agree on
   the same quantized content;
3. the fixed-eval-set quality probe holds greedy top-1 agreement ≥ 99%
   vs fp32 (the ISSUE acceptance bar) and fp32-vs-fp32 is exact;
4. the engine end to end: int8 and fused sweeps complete and match the
   fp32 gather engine's greedy tokens on the bench workload, the
   equal-bytes sizing multiplies resident pages, and kv_dtype /
   attn_impl surface in /debug and /readyz metadata;
5. the WFQ FLOP-priced service clock (VTC's closed deferred item)
   charges prefill and deep-context decode their true cost, and
   degrades to equal-count when flagged off.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import (
    INT8_MAX,
    _quant_decode_write,
    generate,
    init_page_arena,
    kv_quant_probe,
)
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    EngineConfig,
    load_engine_config,
)
from kubernetes_cloud_tpu.serve.paged_kv import (
    kv_bytes_per_token,
    kv_page_bytes,
)
from kubernetes_cloud_tpu.serve.tenancy import (
    TenancyConfig,
    TenantScheduler,
)

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def eval_prompts():
    # THE fixed eval set — imported from the bench so the >=99% bar
    # asserted here and the one bench_serving records can never
    # diverge (conftest puts the repo root on sys.path)
    from scripts.bench_serving import _eval_prompts

    return _eval_prompts()


# ---------------------------------------------------------------------------
# arena round-trip quantization bounds
# ---------------------------------------------------------------------------


def test_quant_roundtrip_error_bound():
    """A written row dequantizes within half a quantization step of the
    original, and rescale drift (scale growth re-quantizing resident
    rows) stays within one further step."""
    rng = np.random.default_rng(0)
    np_pages, ps, hkv, d = 4, 8, 2, 16
    pages = jnp.zeros((np_pages, ps, hkv, d), jnp.int8)
    scale = jnp.zeros((np_pages, hkv), jnp.float32)
    originals = []
    # grow magnitudes so every later write forces a page rescale
    for row in range(ps):
        new = jnp.asarray(rng.standard_normal((1, hkv, d)) * (1 + row),
                          jnp.float32)
        originals.append(np.asarray(new[0]))
        pages, scale = _quant_decode_write(
            pages, scale, jnp.asarray([1]), jnp.asarray([row]), new)
    deq = np.asarray(pages[1].astype(jnp.float32)
                     * scale[1][None, :, None])
    final_step = np.asarray(scale[1])  # fp per int8 step, per head
    for row, orig in enumerate(originals):
        err = np.abs(deq[row] - orig)
        # half a step for the final write; one extra step of rescale
        # drift for rows written before the scale grew
        assert (err <= 1.5 * final_step[:, None] + 1e-7).all(), row
    # scale is the per-head absmax / 127 of the biggest write
    assert float(scale[1].min()) > 0


def test_quantized_arena_structure():
    arena = init_page_arena(CFG, 8, 4, kv_dtype="int8")
    assert arena["k"].dtype == jnp.int8
    assert arena["k_scale"].shape == (CFG.num_layers, 8, CFG.kv_heads)
    with pytest.raises(ValueError):
        init_page_arena(CFG, 8, 4, kv_dtype="fp8")


def test_kv_page_bytes_math():
    # fp32 cache: 2 tensors * ps*Hkv*Dh*4 bytes; int8: 1 byte + scales
    assert kv_page_bytes(16, 2, 64, "fp32", 4) == 2 * 16 * 2 * 64 * 4
    assert kv_page_bytes(16, 2, 64, "int8") == 2 * (16 * 2 * 64 + 4 * 2)
    # int8 quarters the per-token bytes (modulo scale overhead)
    ratio = (kv_bytes_per_token(16, 2, 64, 4, "fp32", 4)
             / kv_bytes_per_token(16, 2, 64, 4, "int8"))
    assert 3.8 < ratio < 4.0


# ---------------------------------------------------------------------------
# quality probe: the measured logit-error budget
# ---------------------------------------------------------------------------


def test_probe_fp32_is_exact(params, eval_prompts):
    probe = kv_quant_probe(CFG, params, eval_prompts[:2],
                           max_new_tokens=4, page_size=8,
                           kv_dtype="fp32")
    assert probe["top1_agreement"] == 1.0
    assert probe["max_logit_err"] == 0.0


def test_probe_int8_meets_budget(params, eval_prompts):
    """The ISSUE acceptance bar: greedy top-1 agreement >= 99% vs fp32
    on the fixed eval set, with the logit error actually measured."""
    probe = kv_quant_probe(CFG, params, eval_prompts,
                           max_new_tokens=10, page_size=8)
    assert probe["top1_agreement"] >= 0.99, probe
    assert probe["max_logit_err"] < 0.1, probe
    assert probe["positions"] == 10 * len(eval_prompts)


@pytest.mark.parametrize("impl", ["pallas", "fused"])
def test_probe_kernels_match_budget(params, eval_prompts, impl):
    """The kernel paths (interpret mode on CPU) honor the same budget
    as the gather fallback — dequant-in-kernel is not a second
    numerics regime."""
    probe = kv_quant_probe(CFG, params, eval_prompts[:2],
                           max_new_tokens=6, page_size=8, impl=impl)
    assert probe["top1_agreement"] >= 0.99, probe
    assert probe["max_logit_err"] < 0.1, probe


# ---------------------------------------------------------------------------
# engine: int8 + fused sweeps
# ---------------------------------------------------------------------------


def make_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    eng = ContinuousBatchingEngine(CFG, params, EngineConfig(**kw),
                                   eos_token_id=None, pad_token_id=0)
    eng.start()
    return eng


def _sweep(eng):
    try:
        reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
                for p, n in zip(PROMPTS, MAX_NEW)]
        return [r.wait(eng) for r in reqs]
    finally:
        eng.stop()


@pytest.fixture(scope="module")
def reference(params):
    refs = []
    for p, n in zip(PROMPTS, MAX_NEW):
        out = np.asarray(generate(CFG, params, jnp.asarray([p], jnp.int32),
                                  max_new_tokens=n, temperature=0.0,
                                  pad_token_id=0))
        refs.append(out[0, len(p):len(p) + n].tolist())
    return refs


def test_int8_engine_sweep_agreement(params, reference):
    """End-to-end int8 engine: every request completes, and on this
    model the measured budget is tight enough that the greedy tokens
    come out identical to fp32 one-shot generation (the probe above is
    the contractual >= 99% bar; identity here is the measured fact for
    this fixed workload)."""
    eng = make_engine(params, kv_dtype="int8")
    outs = _sweep(eng)
    total = agree = 0
    for got, ref in zip(outs, reference):
        assert len(got) == len(ref)
        total += len(ref)
        agree += sum(int(a == b) for a, b in zip(got, ref))
    assert agree / total >= 0.99, (outs, reference)
    assert eng.stats["evictions"] == len(PROMPTS)
    # equal-bytes sizing: the int8 arena holds ~4x the fp32 pages
    fp_pages = EngineConfig(slots=2, max_len=64, paged=True,
                            page_size=8).arena_pages(CFG)
    assert eng._num_pages >= 3.5 * fp_pages


def test_fused_engine_fp32_token_identical(params, reference):
    """attn_impl="fused" (interpret mode on CPU) over an fp32 arena is
    a kernel swap, not a numerics change big enough to flip greedy
    argmax on this workload: tokens match the gather engine's."""
    eng = make_engine(params, attn_impl="fused")
    assert _sweep(eng) == reference


def test_int8_fused_engine_sweep(params, reference):
    """Both tentpole halves composed: quantized arena + fused kernel."""
    eng = make_engine(params, kv_dtype="int8", attn_impl="fused")
    outs = _sweep(eng)
    total = sum(len(r) for r in reference)
    agree = sum(int(a == b) for got, ref in zip(outs, reference)
                for a, b in zip(got, ref))
    assert agree / total >= 0.99


def test_int8_prefix_cache_sharing(params):
    """Prefix pages quantized once are reused across requests: sharing
    still dedups prefill under int8, and shared-page scales are never
    rewritten by the borrowing request (outputs stay within budget)."""
    shared = list(range(200, 224))  # 3 full pages at page_size=8
    prompts = [shared + [t] for t in (5, 6)]
    eng = make_engine(params, kv_dtype="int8")
    try:
        outs = [eng.submit(p, max_new_tokens=5,
                           temperature=0.0).wait(eng) for p in prompts]
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["prefix_tokens_saved"] == 24
        assert all(len(o) == 5 for o in outs)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# metadata surfacing: /debug + /readyz can tell replicas apart
# ---------------------------------------------------------------------------


def test_engine_surfaces_kv_dtype(params):
    eng = make_engine(params, kv_dtype="int8", attn_impl="fused")
    try:
        meta = eng.debug_meta()
        assert meta["kv_dtype"] == "int8"
        assert meta["attn_impl"] == "fused"
        assert meta["kv_bytes_per_token"] == eng.kv_bytes_per_token
        pages = eng.debug_pages()
        assert pages["kv_dtype"] == "int8"
        assert pages["attn_impl"] == "fused"
        assert "quant_probe" not in pages
        eng.note_quant_probe({"top1_agreement": 1.0,
                              "max_logit_err": 0.001})
        assert eng.debug_pages()["quant_probe"]["max_logit_err"] == 0.001
    finally:
        eng.stop()


def test_model_health_carries_rollout_metadata(params):
    from kubernetes_cloud_tpu.serve.continuous import (
        ContinuousBatchingModel,
    )

    class _Svc:
        cfg = CFG
        ready = True
        mesh = None
        tokenizer = None

        def __init__(self, p):
            self.params = p

        def load(self):
            pass

    model = ContinuousBatchingModel(
        "lm", _Svc(params),
        EngineConfig(slots=2, max_len=64, paged=True, page_size=8,
                     kv_dtype="int8"))
    model.load()
    try:
        h = model.health()
        assert h["ok"] and h["kv_dtype"] == "int8"
        assert h["attn_impl"] == "gather"
        assert model.serving_metadata() == {"kv_dtype": "int8",
                                            "attn_impl": "gather",
                                            "role": "colocated",
                                            "mesh_shards": 1,
                                            "prefill_chunk_tokens": 0,
                                            "spec_draft": "none",
                                            "ragged": True}
    finally:
        model.stop()


def test_prediction_reports_kv_dtype(params):
    eng = make_engine(params, kv_dtype="int8")
    try:
        assert eng.ecfg.kv_dtype == "int8"
    finally:
        eng.stop()
    # the per-prediction field rides ContinuousBatchingModel._finish;
    # its value is the engine config's kv_dtype (fp32 when dense)
    assert EngineConfig().kv_dtype == "fp32"


def test_engine_config_kv_dtype_validation(tmp_path):
    with pytest.raises(ValueError):
        EngineConfig(paged=True, kv_dtype="fp8")
    with pytest.raises(ValueError):
        EngineConfig(paged=True, attn_impl="mosaic")
    # model_config.json plumbing
    import json

    (tmp_path / "model_config.json").write_text(json.dumps({
        "continuous_batching": {"paged": True, "kv_dtype": "int8",
                                "attn_impl": "fused", "page_size": 8,
                                "max_len": 64}}))
    cfg = load_engine_config(str(tmp_path))
    assert cfg.kv_dtype == "int8" and cfg.attn_impl == "fused"


# ---------------------------------------------------------------------------
# WFQ per-kind FLOP pricing (VTC deferred item, closed)
# ---------------------------------------------------------------------------


class _Req:
    def __init__(self, tenant="default", lane="interactive"):
        self.tenant = tenant
        self.lane = lane


def test_flop_weighted_prefill_charge():
    sched = TenantScheduler(TenancyConfig(), slots=4)
    sched.set_cost_model(base=1000.0, per_ctx=10.0)
    st = sched.state("default")
    sched.charge_prefill(_Req(), 8)
    # span cost: 8 + (10/1000) * (8*9/2) = 8.36 decode-equivalents
    assert sched._vt(st) == pytest.approx(8.36)
    # a cache hit charges only the tail, but at its DEEP context price
    sched2 = TenantScheduler(TenancyConfig(), slots=4)
    sched2.set_cost_model(base=1000.0, per_ctx=10.0)
    sched2.charge_prefill(_Req(), 8, start=100)
    # 8 + 0.01*(8*100 + 36) = 16.36
    assert sched2._vt(sched2.state("default")) == pytest.approx(16.36)


def test_flop_weighted_decode_charge():
    sched = TenantScheduler(TenancyConfig(), slots=4)
    sched.set_cost_model(base=1000.0, per_ctx=10.0)
    st = sched.state("default")
    sched.charge_decode(_Req(), ctx=101)
    # one token at context 101: 1 + 0.01*101 = 2.01
    assert sched._vt(st) == pytest.approx(2.01)
    sched.charge_decode(_Req())  # legacy flat charge without ctx
    assert sched._vt(st) == pytest.approx(3.01)


def test_flop_pricing_flag_off_is_legacy():
    cfg = TenancyConfig(flop_weighted_cost=False)
    sched = TenantScheduler(cfg, slots=4)
    sched.set_cost_model(base=1000.0, per_ctx=10.0)
    sched.charge_prefill(_Req(), 8, start=100)
    sched.charge_decode(_Req(), ctx=101)
    assert sched._vt(sched.state("default")) == pytest.approx(9.0)


def test_unwired_cost_model_is_legacy():
    sched = TenantScheduler(TenancyConfig(), slots=4)
    sched.charge_prefill(_Req(), 8, start=100)
    assert sched._vt(sched.state("default")) == pytest.approx(8.0)


def test_parse_tenancy_flag():
    from kubernetes_cloud_tpu.serve.tenancy import parse_tenancy

    cfg = parse_tenancy({"tenants": []})
    assert cfg.flop_weighted_cost is True
    cfg = parse_tenancy({"flop_weighted_cost": False})
    assert cfg.flop_weighted_cost is False
