"""Tensor-parallel serving correctness — the BLOOM-176B pattern at tiny
scale (reference ``online-inference/bloom-176b-deepspeed`` serves with
fused TP kernels over 8 GPUs; here TP is a mesh axis and XLA collectives,
and the test proves sharded serving is bit-identical to single-device).
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.models.causal_lm import (
    CausalLMConfig,
    init_params,
)
from kubernetes_cloud_tpu.serve.lm_service import CausalLMService
from kubernetes_cloud_tpu.weights.tensorstream import write_pytree

# BLOOM-family architecture: alibi positions, serial residual,
# post-embedding layernorm (SURVEY.md §2.1 #16-17).
BLOOM_TINY = CausalLMConfig(
    vocab_size=288, hidden_size=64, num_layers=2, num_heads=4,
    pos_emb="alibi", parallel_residual=False, embed_layernorm=True,
    act="gelu_tanh", max_seq_len=128)

PROMPTS = ["tensor parallel serving", "b"]
GREEDY = {"MAX_NEW_TOKENS": 8, "TEMPERATURE": 0.0, "TOP_K": 0,
          "TOP_P": 1.0, "SEED": 0, "ECHO_PROMPT": False}


@pytest.fixture(scope="module")
def bloom_params():
    return init_params(BLOOM_TINY, jax.random.key(42))


def _texts(svc):
    return svc.generate_texts(PROMPTS, GREEDY)


def test_tp_matches_single_device(bloom_params, devices8):
    ref = CausalLMService("ref", BLOOM_TINY, params=bloom_params,
                          dtype=jnp.float32)
    ref.load()
    want = _texts(ref)

    mesh = build_mesh(MeshSpec(model=4, fsdp=2), devices=devices8)
    tp = CausalLMService("tp", BLOOM_TINY, params=bloom_params, mesh=mesh,
                         dtype=jnp.float32)
    tp.load()
    got = _texts(tp)
    assert got == want

    # Each device holds only its parameter shard: the point of TP serving
    # (176B does not fit one chip).  Embedding rows shard over fsdp and
    # hidden over model, so every leaf shard must be < the full leaf.
    qkv = tp.params["blocks"]["attn"]["wqkv"]
    shard_elems = max(s.data.size for s in qkv.addressable_shards)
    assert shard_elems < qkv.size


def test_tp_sharded_stream_load(tmp_path, bloom_params, devices8):
    """Serialize → stream-load directly into the sharded layout (the
    GCS→sharded-HBM cold-start path, SURVEY.md §7 hard part 2)."""
    path = os.path.join(tmp_path, "bloom.tensors")
    write_pytree(path, bloom_params)

    ref = CausalLMService("ref", BLOOM_TINY, params=bloom_params,
                          dtype=jnp.float32)
    ref.load()

    mesh = build_mesh(MeshSpec(model=2, fsdp=2, data=2), devices=devices8)
    svc = CausalLMService("stream", BLOOM_TINY, weights_path=path,
                          mesh=mesh, dtype=jnp.float32)
    svc.load()
    assert svc.ready
    assert _texts(svc) == _texts(ref)


def test_tp_gptj_style_config(devices8):
    """Second family through the same path: GPT-J (rope interleaved,
    parallel residual — the FasterTransformer-served model, #19)."""
    cfg = CausalLMConfig(vocab_size=288, hidden_size=64, num_layers=2,
                         num_heads=4, pos_emb="rope", rope_interleaved=True,
                         parallel_residual=True, max_seq_len=128)
    params = init_params(cfg, jax.random.key(7))
    ref = CausalLMService("ref", cfg, params=params, dtype=jnp.float32)
    ref.load()
    mesh = build_mesh(MeshSpec(model=4), devices=devices8[:4])
    tp = CausalLMService("tp", cfg, params=params, mesh=mesh,
                         dtype=jnp.float32)
    tp.load()
    assert _texts(tp) == _texts(ref)
