"""Ragged token-level dispatch: ONE flat hybrid batch as THE iteration.

The lock (serve/continuous.py ``_RaggedPass``/``_flush_ragged``,
models/generate.py ``ragged_step_pages``): a ragged engine must produce
greedy outputs bitwise-identical to the padded multi-program engine for
the same requests across the whole feature matrix — chunked prefill,
speculative decoding, int8 KV, prefix sharing + copy-on-write, TP mesh,
preemption/resume — while issuing exactly ONE device program per
scheduler pass (asserted through the ``kct_engine_dispatches_total``
accounting) on a bounded pow-2 shape ladder.  Stochastic speculation
(temperature > 0 slots now speculate, via rejection sampling) is locked
distribution-exactly: statistically against the non-speculative
sampler, and bitwise in the top_k=1 degenerate case.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu import faults
from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.faults import FaultSpec
from kubernetes_cloud_tpu.models import PRESETS, init_params
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.serve.errors import EngineRestartedError
from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    EngineConfig,
)
from kubernetes_cloud_tpu.serve.spec_decode import ModelDraft
from kubernetes_cloud_tpu.serve.supervisor import (
    ServingSupervisor,
    SupervisorConfig,
)
from kubernetes_cloud_tpu.serve.tenancy import TenancyConfig, TenantSpec

CFG = dataclasses.replace(PRESETS["test-tiny"], vocab_size=512,
                          dtype=jnp.float32)

PROMPTS = [list(range(1, 9)), list(range(40, 45)),
           list(range(100, 120)), [7, 8, 9]]
MAX_NEW = [6, 9, 4, 7]

TEN = TenancyConfig(
    tenants=(
        TenantSpec("batchy", lane="batch", api_keys=("k-batchy",)),
        TenantSpec("inter", lane="interactive", api_keys=("k-inter",)),
    ),
    min_batch_progress=2,
)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def ref_tokens(params, prompt, n):
    out = np.asarray(generate(CFG, params,
                              jnp.asarray([prompt], jnp.int32),
                              max_new_tokens=n, temperature=0.0,
                              pad_token_id=0))
    return out[0, len(prompt):len(prompt) + n].tolist()


def make_engine(params, ragged=True, mesh=None, draft=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    eng = ContinuousBatchingEngine(CFG, params,
                                   EngineConfig(ragged=ragged, **kw),
                                   eos_token_id=None, pad_token_id=0,
                                   mesh=mesh, draft=draft)
    eng.start()
    return eng


def run_greedy(eng):
    reqs = [eng.submit(p, max_new_tokens=n, temperature=0.0)
            for p, n in zip(PROMPTS, MAX_NEW)]
    return [r.wait(eng) for r in reqs]


# ---------------------------------------------------------------------------
# the oracle: ragged outputs == padded outputs across the feature matrix
# ---------------------------------------------------------------------------


MATRIX = {
    "plain": {},
    "chunked": {"prefill_chunk_tokens": 6},
    "spec": {"spec_draft": "ngram", "spec_k": 3},
    "int8": {"kv_dtype": "int8"},
    "chunk+spec+int8": {"prefill_chunk_tokens": 6, "spec_draft": "ngram",
                        "spec_k": 3, "kv_dtype": "int8"},
}


@pytest.mark.parametrize("feature", sorted(MATRIX))
def test_token_identity_vs_padded_engine(params, feature):
    """Composition sweep: the flat-batch program and its scheduler
    rewiring must be invisible in the tokens for every feature the
    padded engine composes."""
    kw = MATRIX[feature]
    base = make_engine(params, ragged=False, **kw)
    try:
        want = run_greedy(base)
    finally:
        base.stop()
    eng = make_engine(params, ragged=True, **kw)
    try:
        assert run_greedy(eng) == want
        assert eng.stats["dispatches"] > 0
    finally:
        eng.stop()


def test_stochastic_non_spec_identity(params):
    """Without a draft, temperature > 0 sampling consumes the slot RNG
    identically in both engines (same logits rows, same host sampler),
    so even stochastic outputs are bitwise-equal."""
    def run(ragged):
        eng = make_engine(params, ragged=ragged)
        try:
            reqs = [eng.submit(p, max_new_tokens=n, temperature=0.8,
                               seed=i)
                    for i, (p, n) in enumerate(zip(PROMPTS, MAX_NEW))]
            return [r.wait(eng) for r in reqs]
        finally:
            eng.stop()

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# ISSUE acceptance: one device dispatch per hybrid scheduler pass
# ---------------------------------------------------------------------------


def test_one_device_dispatch_per_pass(params):
    """A mixed chunk+spec workload must drive the device through the
    ragged program ONLY — one launch per pass, counted by the
    dispatches counter — with the padded programs never invoked."""
    eng = make_engine(params, prefill_chunk_tokens=6,
                      spec_draft="ngram", spec_k=3)
    calls = {"n": 0}
    orig = eng._ragged_pages

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    def forbidden(*a, **kw):
        raise AssertionError("padded program dispatched under ragged")

    eng._ragged_pages = counting
    eng._decode_pages = forbidden
    eng._prefill_pages = forbidden
    eng._verify_pages = forbidden
    eng._copy_pages = forbidden
    try:
        outs = run_greedy(eng)
        assert outs == [ref_tokens(params, p, n)
                        for p, n in zip(PROMPTS, MAX_NEW)]
        # every launch was the flat-batch program, and every one was
        # counted: the dispatch counter IS the device launch count
        assert calls["n"] > 0
        assert eng.stats["dispatches"] == calls["n"]
    finally:
        eng.stop()


def test_geometry_ladder_bounds_compiled_shapes(params):
    """The flat batch pads to pow-2 rungs (floor 8), so a whole mixed
    workload compiles a handful of shapes, not one per composition."""
    eng = make_engine(params, prefill_chunk_tokens=6,
                      spec_draft="ngram", spec_k=3)
    try:
        run_greedy(eng)
        rungs = [k for k in eng._warm_shapes
                 if isinstance(k, tuple) and k[0] == "ragged"]
        assert rungs, "no ragged shapes warmed"
        for _, n_b, m_b, c_b in rungs:
            assert n_b >= 8 and (n_b & (n_b - 1)) == 0
            assert m_b >= 8 and (m_b & (m_b - 1)) == 0
            assert c_b % 8 == 0
        # log-many: this workload spans prompts of 3..20 tokens plus
        # spec verification — a per-shape compile would be dozens
        assert len(rungs) <= 8
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# prefix sharing + copy-on-write ride inside the flat program
# ---------------------------------------------------------------------------


def test_prefix_sharing_and_cow_identity(params):
    """A page-aligned repeat prompt takes the COW path (full-prompt
    match goes private for its last-token write) with the copy executed
    as the ragged program's prologue — tokens and cache accounting must
    match the padded engine's."""
    prompt = list(range(1, 17))  # 2 full pages at page_size=8

    def run(ragged):
        eng = make_engine(params, ragged=ragged)
        try:
            first = eng.submit(prompt, max_new_tokens=5,
                               temperature=0.0).wait(eng)
            second = eng.submit(prompt, max_new_tokens=5,
                                temperature=0.0).wait(eng)
            return first, second, dict(eng.stats)
        finally:
            eng.stop()

    f_r, s_r, st_r = run(True)
    f_p, s_p, st_p = run(False)
    assert (f_r, s_r) == (f_p, s_p)
    assert f_r == s_r == ref_tokens(params, prompt, 5)
    for st in (st_r, st_p):
        assert st["prefix_hits"] >= 1
        assert st["cow_copies"] >= 1


# ---------------------------------------------------------------------------
# preemption / resume (QoS lanes) composes with the flat batch
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_preempt_resume_identity_under_ragged(params):
    """An interactive arrival preempts a batch slot mid-decode; the
    victim resumes (pinned pages, prefill-free) and both finish
    bitwise-identical to one-shot generate — with chunked prefill in
    the same passes for good measure."""
    eng = make_engine(params, tenancy=TEN, prefill_chunk_tokens=6)
    b_prompts = [list(range(1, 9)), list(range(40, 45))]
    i_prompt = [7, 8, 9]
    try:
        victims = [eng.submit(p, max_new_tokens=40, temperature=0.0,
                              api_key="k-batchy") for p in b_prompts]
        for v in victims:  # both slots decoding before the arrival
            next(v.iter_tokens(timeout=60))
        pre = eng.submit(i_prompt, max_new_tokens=7, temperature=0.0,
                         api_key="k-inter")
        assert pre.wait(eng) == ref_tokens(params, i_prompt, 7)
        for p, v in zip(b_prompts, victims):
            assert v.wait(eng) == ref_tokens(params, p, 40)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["resumed"] == eng.stats["preemptions"]
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# TP mesh: the single shard_map ragged program
# ---------------------------------------------------------------------------


def test_tp_mesh_ragged_identity(params):
    """On a 2-shard model mesh the ragged engine runs ONE shard_map
    program (models/tp_decode.build_tp_ragged_program) — outputs must
    match the single-chip ragged engine bitwise."""
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("need 2 cpu devices")
    mesh = build_mesh(MeshSpec(data=1, model=2), devices=devs[:2])
    single = make_engine(params)
    try:
        want = run_greedy(single)
    finally:
        single.stop()
    eng = make_engine(params, mesh=mesh)
    try:
        assert eng.mesh_shards == 2
        assert run_greedy(eng) == want
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# chaos: the pass dies mid-flush → supervisor restart, queued work moves
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_supervisor_restart_mid_ragged_pass(params):
    """An injected crash inside the flat-batch dispatch kills the
    engine mid-pass; the supervisor restarts it, in-flight requests
    fail retryably, and queued (never-admitted) work transplants to
    the replacement and completes token-identically."""
    class _Shim:
        def __init__(self, engine):
            self.engine = engine
            self.name, self.ready = "lm", True
            self.cfg = engine.ecfg

        def load(self):
            self.engine = make_engine(params, slots=1)

    shim = _Shim(make_engine(params, slots=1))
    # compile everything the scenario hits before arming the fault
    shim.engine.submit([1, 2, 3], max_new_tokens=2,
                       temperature=0.0).wait()
    sup = ServingSupervisor(SupervisorConfig(poll_interval_s=0.05,
                                             hang_timeout_s=0.25))
    sup.watch(shim)
    sup.start()
    try:
        prompt_a, prompt_b = list(range(1, 9)), [7, 8, 9]
        want_b = ref_tokens(params, prompt_b, 4)
        # the ragged engine fires model_fn once per flush: crash the
        # third pass, when A is mid-generation and B still queued
        faults.install(faults.FaultInjector(
            [FaultSpec("model_fn", at=3)]))
        req_a = shim.engine.submit(prompt_a, max_new_tokens=30,
                                   temperature=0.0)
        req_b = shim.engine.submit(prompt_b, max_new_tokens=4,
                                   temperature=0.0)
        with pytest.raises(EngineRestartedError):
            req_a.wait()
        assert req_b.wait() == want_b  # transplanted, then completed
        assert sup.stats["restarts"] >= 1
        assert req_b.engine is shim.engine  # follows the replacement
    finally:
        faults.uninstall()
        sup.stop()
        shim.engine.stop()


# ---------------------------------------------------------------------------
# stochastic speculation: rejection sampling is distribution-exact
# ---------------------------------------------------------------------------


def _sample_matrix(params, draft_for, n_runs, **submit_kw):
    """Joint (t1, t2, t3) samples over many seeds, one engine."""
    eng = make_engine(params, slots=4,
                      draft=(draft_for(params) if draft_for else None))
    outs = []
    try:
        pending = []
        for seed in range(n_runs):
            pending.append(eng.submit(list(range(1, 9)),
                                      max_new_tokens=3, seed=seed,
                                      **submit_kw))
            if len(pending) >= 16:
                outs.extend(tuple(r.wait(eng)) for r in pending)
                pending = []
        outs.extend(tuple(r.wait(eng)) for r in pending)
        stats = dict(eng.stats)
    finally:
        eng.stop()
    return outs, stats


def _self_draft(params):
    return ModelDraft(CFG, params, slots=4, max_len=64, pad_token_id=0)


@pytest.mark.slow
def test_stochastic_spec_distribution_exact(params):
    """The distribution lock for rejection sampling: the empirical
    joint distribution of 3-token stochastic generations under
    speculation (draft == target, so proposals are live every round)
    must match the non-speculative sampler's.  top_k=2 keeps the joint
    support at 8 outcomes so 600 draws resolve it: both sides are
    deterministic given the seed list, measured total variation is
    0.030 against a same-distribution split-half noise floor of
    ~0.08, and any systematic acceptance bias (e.g. always accepting
    the draft) collapses the joint toward the greedy chain and
    measures far above the bound."""
    n = 600
    kw = dict(temperature=1.0, top_k=2)
    spec, st = _sample_matrix(params, _self_draft, n, **kw)
    plain, _ = _sample_matrix(params, None, n, **kw)
    assert st["spec_drafted"] > 0  # speculation actually engaged
    assert st["spec_accepted"] > 0
    support = set(spec) | set(plain)
    tv = 0.5 * sum(abs(spec.count(t) / n - plain.count(t) / n)
                   for t in support)
    assert tv < 0.15, f"total variation {tv:.3f}"


def test_stochastic_spec_topk1_bitwise(params):
    """Degenerate exactness: top_k=1 makes the filtered distribution a
    point mass, so rejection sampling must reproduce the argmax chain
    bitwise — accept when the draft IS the argmax, and the residual
    fallback lands on the argmax when it is not."""
    want = ref_tokens(params, list(range(1, 9)), 6)
    eng = make_engine(params, draft=_self_draft(params))
    try:
        got = eng.submit(list(range(1, 9)), max_new_tokens=6,
                         temperature=1.0, top_k=1, seed=3).wait(eng)
        assert got == want
        assert eng.stats["spec_rounds"] > 0
    finally:
        eng.stop()
