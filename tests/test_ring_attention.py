"""Ring attention vs. dense attention on the CPU-simulated seq mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.ops.attention import attention
from kubernetes_cloud_tpu.ops.ring_attention import ring_attention


def _rand_qkv(rng, b=2, s=256, h=4, hkv=None, dh=16):
    kq, kk, kv = jax.random.split(rng, 3)
    hkv = hkv or h
    q = jax.random.normal(kq, (b, s, h, dh), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, dh), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, dh), jnp.float32)
    return q, k, v


@pytest.fixture
def seq_mesh(devices8):
    return build_mesh(MeshSpec(data=1, seq=8), devices=devices8)


def test_ring_matches_dense_causal(seq_mesh):
    q, k, v = _rand_qkv(jax.random.key(0))
    want = attention(q, k, v, causal=True, impl="xla")
    got = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_with_padding_mask(seq_mesh):
    q, k, v = _rand_qkv(jax.random.key(1))
    mask = jnp.ones((2, 256), jnp.int32).at[:, 200:].set(0)
    want = attention(q, k, v, causal=True, mask=mask, impl="xla")
    got = ring_attention(q, k, v, seq_mesh, causal=True, kv_mask=mask)
    # Fully-masked key rows only; compare where queries attend to anything.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_non_causal(seq_mesh):
    q, k, v = _rand_qkv(jax.random.key(2))
    want = attention(q, k, v, causal=False, impl="xla")
    got = ring_attention(q, k, v, seq_mesh, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa(seq_mesh):
    q, k, v = _rand_qkv(jax.random.key(3), h=8, hkv=2)
    want = attention(q, k, v, causal=True, impl="xla")
    got = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_under_jit_grad(seq_mesh):
    """Ring attention must be differentiable and jittable (training path)."""
    q, k, v = _rand_qkv(jax.random.key(4), b=1, s=64, h=2, dh=8)

    @jax.jit
    def loss_ring(q, k, v):
        return ring_attention(q, k, v, seq_mesh, causal=True).sum()

    def loss_dense(q, k, v):
        return attention(q, k, v, causal=True, impl="xla").sum()

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-4, atol=1e-4)
