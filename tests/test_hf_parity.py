"""Logits parity vs HuggingFace transformers (torch CPU) for every model
family the framework imports — the strongest architecture-correctness test
(golden-value tests per SURVEY.md §4)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from kubernetes_cloud_tpu.models.causal_lm import forward  # noqa: E402
from kubernetes_cloud_tpu.weights.hf_import import (  # noqa: E402
    config_from_hf,
    import_state_dict,
)


def _parity(hf_model, arch, atol=2e-4):
    hf_model.eval()
    cfg = config_from_hf(hf_model.config)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = import_state_dict(cfg, hf_model.state_dict(), arch)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 24))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(forward(cfg, params, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, atol=atol, rtol=1e-3)


def test_gpt_neox_parity():
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, hidden_act="gelu")
    _parity(transformers.GPTNeoXForCausalLM(hf_cfg), "gpt_neox")


def test_gpt_neox_serial_residual_parity():
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=64, rotary_pct=1.0,
        use_parallel_residual=False, hidden_act="gelu")
    _parity(transformers.GPTNeoXForCausalLM(hf_cfg), "gpt_neox")


def test_gptj_parity():
    hf_cfg = transformers.GPTJConfig(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, rotary_dim=8,
        n_positions=64, n_inner=None)
    _parity(transformers.GPTJForCausalLM(hf_cfg), "gptj")


def test_bloom_parity():
    hf_cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4)
    _parity(transformers.BloomForCausalLM(hf_cfg), "bloom")


def test_gpt2_parity():
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64)
    _parity(transformers.GPT2LMHeadModel(hf_cfg), "gpt2")
