// batch_reader — native mmap token-dataset reader with threaded gather.
//
// The training hot path reads shuffled rows out of the flat uint16
// context file (format producer: csrc/dataset_tokenizer; consumer
// semantics: finetuner-workflow/finetuner/finetuner.py:633-695 — the
// reference does this per-row in Python through numpy's mmap).  This
// library does the per-batch work natively and GIL-free:
//
//   * mmap + MADV_RANDOM on open (shuffled access pattern);
//   * br_prefetch: MADV_WILLNEED on the next batch's rows so page-ins
//     overlap device compute;
//   * br_gather: N worker threads copy rows, widen uint16 -> int32 and
//     derive the trailing-pad attention mask in one pass.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread batch_reader.cpp \
//        -o libbatch_reader.so

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Reader {
  int fd = -1;
  const uint16_t* data = nullptr;
  size_t nbytes = 0;
  int64_t context_size = 0;
  int64_t num_rows = 0;
};

long page_size() {
  static long ps = sysconf(_SC_PAGESIZE);
  return ps;
}

}  // namespace

extern "C" {

void* br_open(const char* path, int64_t context_size) {
  if (context_size <= 0) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0 ||
      st.st_size % (context_size * 2) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(map, st.st_size, MADV_RANDOM);
  auto* r = new Reader;
  r->fd = fd;
  r->data = static_cast<const uint16_t*>(map);
  r->nbytes = st.st_size;
  r->context_size = context_size;
  r->num_rows = st.st_size / (context_size * 2);
  return r;
}

int64_t br_num_rows(const void* h) {
  return h ? static_cast<const Reader*>(h)->num_rows : -1;
}

// Copy rows[0..n) into out_ids[n, context_size] (int32) and, when
// pad_token >= 0, write the trailing-pad attention mask into
// out_mask[n, context_size] (int32; may be null).  Returns 0 on success,
// -1 on a bad row index.
int br_gather(const void* h, const int64_t* rows, int64_t n,
              int32_t* out_ids, int32_t* out_mask, int32_t pad_token,
              int n_threads) {
  const auto* r = static_cast<const Reader*>(h);
  if (!r) return -1;
  const int64_t c = r->context_size;
  std::atomic<bool> ok(true);

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t row = rows[i];
      if (row < 0 || row >= r->num_rows) {
        ok.store(false, std::memory_order_relaxed);
        return;
      }
      const uint16_t* src = r->data + row * c;
      int32_t* dst = out_ids + i * c;
      for (int64_t j = 0; j < c; ++j) dst[j] = src[j];
      if (out_mask != nullptr) {
        int32_t* m = out_mask + i * c;
        if (pad_token < 0) {
          for (int64_t j = 0; j < c; ++j) m[j] = 1;
        } else {
          // trailing pad run is masked; mid-row pads stay visible
          int64_t last_real = -1;
          for (int64_t j = c - 1; j >= 0; --j) {
            if (src[j] != static_cast<uint16_t>(pad_token)) {
              last_real = j;
              break;
            }
          }
          for (int64_t j = 0; j < c; ++j) m[j] = j <= last_real ? 1 : 0;
        }
      }
    }
  };

  int nt = std::max(1, std::min<int>(n_threads, n));
  if (nt == 1) {
    work(0, n);
  } else {
    std::vector<std::thread> threads;
    const int64_t chunk = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = std::min<int64_t>(n, lo + chunk);
      if (lo >= hi) break;
      threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  return ok.load() ? 0 : -1;
}

// Advise the kernel to page in the given rows (next batch) while the
// device crunches the current one.
void br_prefetch(const void* h, const int64_t* rows, int64_t n) {
  const auto* r = static_cast<const Reader*>(h);
  if (!r) return;
  const long ps = page_size();
  const int64_t row_bytes = r->context_size * 2;
  for (int64_t i = 0; i < n; ++i) {
    if (rows[i] < 0 || rows[i] >= r->num_rows) continue;
    auto addr = reinterpret_cast<uintptr_t>(r->data) + rows[i] * row_bytes;
    uintptr_t aligned = addr & ~static_cast<uintptr_t>(ps - 1);
    size_t len = (addr - aligned) + row_bytes;
    madvise(reinterpret_cast<void*>(aligned), len, MADV_WILLNEED);
  }
}

void br_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (!r) return;
  munmap(const_cast<uint16_t*>(r->data), r->nbytes);
  ::close(r->fd);
  delete r;
}

}  // extern "C"
