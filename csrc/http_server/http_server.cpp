// http_server — native HTTP/1.1 serving front-end.
//
// The reference's serving cores are C++ (TF-Serving for the SavedModel
// services, Triton for FasterTransformer); the Python layer only defines
// the model.  Same split here: this library owns sockets, connection
// concurrency, HTTP parsing and keep-alive in native threads, and calls
// up into the embedding runtime through a single C callback per request
// (ctypes serializes callback entry on the GIL, which matches the
// one-device-program-at-a-time serving model; all I/O with slow clients
// happens in native threads that never hold the GIL).
//
// C ABI (for ctypes; no pybind11 in the image):
//   handle = hs_start(port, backlog, handler)
//   hs_port(handle)            actual bound port (0 => ephemeral)
//   hs_stop(handle)
// handler signature:
//   void handler(const char* method, const char* path,
//                const char* headers,  // raw header block, NUL-terminated
//                const char* body, long body_len, void* resp);
// the handler MUST call exactly once:
//   hs_respond(resp, status, content_type, body, body_len)
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread http_server.cpp \
//        -o libhttp_server.so

#include <atomic>
#include <cctype>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

using Handler = void (*)(const char*, const char*, const char*,
                         const char*, long, void*);

struct Response {
  int status = 500;
  std::string content_type = "application/json";
  std::string body = "{\"error\": \"handler did not respond\"}";
  bool responded = false;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  Handler handler = nullptr;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  // Open connections are tracked (not detached) so hs_stop can shut
  // down their fds and join every thread before the Server is freed —
  // a detached keep-alive thread would otherwise dereference a dangling
  // Server* (and possibly call into Python) after shutdown.
  std::mutex mu;
  std::unordered_map<int, std::thread> conns;   // fd -> serving thread
  std::vector<std::thread> finished;            // exited, awaiting join
};

const char* reason(int status) {
  switch (status) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 503: return "Service Unavailable";
    default: return status < 500 ? "Client Error" : "Internal Server Error";
  }
}

// Read until the full header + Content-Length body is in `buf`.
// Returns false on EOF/error/oversize.
bool read_request(int fd, std::string& buf, size_t& header_end,
                  size_t& content_len) {
  constexpr size_t kMax = 64u << 20;  // 64 MiB request cap
  char tmp[16384];
  header_end = std::string::npos;
  content_len = 0;
  while (true) {
    if (header_end == std::string::npos) {
      size_t pos = buf.find("\r\n\r\n");
      if (pos != std::string::npos) {
        header_end = pos + 4;
        // parse Content-Length (case-insensitive)
        for (size_t i = 0; i + 15 < header_end;) {
          size_t eol = buf.find("\r\n", i);
          if (eol == std::string::npos || eol > header_end) break;
          if (eol - i > 15 &&
              strncasecmp(buf.c_str() + i, "content-length:", 15) == 0) {
            content_len = strtoul(buf.c_str() + i + 15, nullptr, 10);
          }
          i = eol + 2;
        }
        if (content_len > kMax) return false;
      }
    }
    if (header_end != std::string::npos &&
        buf.size() >= header_end + content_len) {
      return true;
    }
    ssize_t n = recv(fd, tmp, sizeof(tmp), 0);
    if (n <= 0) return false;
    if (buf.size() + n > kMax) return false;
    buf.append(tmp, n);
  }
}

bool write_all(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = send(fd, data, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    data += n;
    len -= n;
  }
  return true;
}

void serve_connection(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string buf;
  while (!s->stopping.load(std::memory_order_relaxed)) {
    size_t header_end, content_len;
    if (!read_request(fd, buf, header_end, content_len)) break;

    // request line: METHOD SP PATH SP VERSION
    size_t sp1 = buf.find(' ');
    size_t sp2 = sp1 == std::string::npos ? std::string::npos
                                          : buf.find(' ', sp1 + 1);
    std::string method = sp1 == std::string::npos ? "" : buf.substr(0, sp1);
    std::string path = sp2 == std::string::npos
                           ? "/"
                           : buf.substr(sp1 + 1, sp2 - sp1 - 1);
    // HTTP version is the third request-line token exactly (a body or
    // path containing "HTTP/1.1" must not flip the decision), and the
    // Connection header is matched case-insensitively in the headers.
    size_t line_end = buf.find("\r\n");
    bool keep_alive = false;
    if (line_end != std::string::npos && sp2 != std::string::npos &&
        sp2 < line_end) {
      keep_alive = buf.compare(sp2 + 1, line_end - sp2 - 1,
                               "HTTP/1.1") == 0;
    }
    for (size_t i = line_end == std::string::npos ? header_end
                                                  : line_end + 2;
         i + 11 < header_end;) {
      size_t eol = buf.find("\r\n", i);
      if (eol == std::string::npos || eol > header_end) break;
      if (strncasecmp(buf.c_str() + i, "connection:", 11) == 0) {
        std::string val = buf.substr(i + 11, eol - i - 11);
        for (auto& c : val) c = static_cast<char>(tolower(c));
        if (val.find("close") != std::string::npos) keep_alive = false;
        else if (val.find("keep-alive") != std::string::npos)
          keep_alive = true;  // HTTP/1.0 opt-in
      }
      i = eol + 2;
    }

    Response resp;
    if (s->handler) {
      // Raw header block (request line included; the Python side skips
      // colon-less lines) so the data plane can read per-request
      // metadata like X-Request-Deadline-Ms without reparsing sockets.
      std::string header_blk = buf.substr(0, header_end);
      s->handler(method.c_str(), path.c_str(), header_blk.c_str(),
                 buf.c_str() + header_end,
                 static_cast<long>(content_len), &resp);
    }
    char head[256];
    int hn = snprintf(head, sizeof(head),
                      "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                      "Content-Length: %zu\r\nConnection: %s\r\n\r\n",
                      resp.status, reason(resp.status),
                      resp.content_type.c_str(), resp.body.size(),
                      keep_alive ? "keep-alive" : "close");
    // snprintf returns the untruncated would-be length (or negative on
    // output error); clamp both sides so an oversized content_type can't
    // read past the stack buffer and a negative hn can't become a huge
    // size_t in write_all.
    if (hn < 0) break;
    if (hn > (int)sizeof(head) - 1) hn = (int)sizeof(head) - 1;
    if (!write_all(fd, head, hn) ||
        !write_all(fd, resp.body.data(), resp.body.size())) {
      break;
    }
    buf.erase(0, header_end + content_len);
    if (!keep_alive) break;
  }
  // Deregister and close under the lock: hs_stop also touches conn fds
  // under s->mu, so the fd can't be shut down concurrently with (or
  // after) its close here, and a recycled fd number can't be hit.
  // Earlier-exited threads are reaped here too (never self — self is
  // pushed after the swap), so an idle server holds at most one exited
  // thread's resources, not a whole burst's.
  std::vector<std::thread> reap;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    close(fd);
    auto it = s->conns.find(fd);
    if (it != s->conns.end()) {
      reap.swap(s->finished);
      s->finished.push_back(std::move(it->second));
      s->conns.erase(it);
    }
  }
  for (auto& t : reap) t.join();
}

void accept_loop(Server* s) {
  while (!s->stopping.load(std::memory_order_relaxed)) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stopping.load(std::memory_order_relaxed)) return;
      continue;
    }
    // thread-per-connection: connections are few and long-lived behind
    // Knative; native threads block on slow clients, not the GIL.
    std::vector<std::thread> reap;
    {
      std::lock_guard<std::mutex> lk(s->mu);
      // Register under the lock: the new thread's exit path takes s->mu
      // before looking itself up, so it cannot race its own insertion.
      s->conns.emplace(fd, std::thread(serve_connection, s, fd));
      reap.swap(s->finished);
    }
    for (auto& t : reap) t.join();
  }
}

}  // namespace

extern "C" {

void hs_respond(void* resp_ptr, int status, const char* content_type,
                const char* body, long body_len) {
  auto* r = static_cast<Response*>(resp_ptr);
  r->status = status;
  if (content_type) r->content_type = content_type;
  r->body.assign(body ? body : "", body ? body_len : 0);
  r->responded = true;
}

void* hs_start(int port, int backlog, Handler handler) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, backlog > 0 ? backlog : 128) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);

  auto* s = new Server;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->handler = handler;
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

int hs_port(const void* h) {
  return h ? static_cast<const Server*>(h)->port : -1;
}

void hs_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  if (!s) return;
  s->stopping.store(true);
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  s->accept_thread.join();  // no further registrations after this
  std::vector<std::thread> pending;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (auto& kv : s->conns) {
      shutdown(kv.first, SHUT_RDWR);  // wake any blocked recv/send
      pending.push_back(std::move(kv.second));
    }
    s->conns.clear();
    for (auto& t : s->finished) pending.push_back(std::move(t));
    s->finished.clear();
  }
  // Every connection thread exits (closing its own fd) before the
  // Server — and with it the Python-side handler — goes away.
  for (auto& t : pending) t.join();
  delete s;
}

}  // extern "C"
