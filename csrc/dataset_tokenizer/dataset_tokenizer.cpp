// dataset_tokenizer — streaming corpus -> packed uint16 token contexts.
//
// Native C++ replacement for the Go `gpt_bpe` dataset_tokenizer the
// reference launches as a container step (invocation + flag semantics:
// finetuner-workflow/finetune-workflow.yaml:188-191,441-454; flag docs
// :39-81).  Emits the flat little-endian uint16 context-row format the
// trainer mmaps (consumer spec: finetuner-workflow/finetuner/
// finetuner.py:633-695), plus a JSON sidecar with the packing metadata.
//
// Tokenizers:
//   --tokenizer byte   ids 0-255 are raw bytes (no vocab files needed)
//   --tokenizer bpe    byte-level BPE from --vocab vocab.json and
//                      --merges merges.txt (GPT-2 file formats)
//
// Packing semantics:
//   * each input file is one document; documents are tokenized, an
//     --eot-token is appended after each, and the stream is packed into
//     rows of --context-size tokens;
//   * if --boundary-token >= 0 and a row boundary would split a document,
//     the row is cut at the document's last boundary token at row index
//     >= --boundary-overlap, and the next row resumes right after that
//     boundary (keeps contexts aligned to sentence/paragraph boundaries);
//   * the final partial row is padded with --pad-token;
//   * --sampling P keeps each document with probability P% (seeded);
//   * --reorder none|shuffle|reverse orders documents before packing;
//   * --sanitize collapses runs of whitespace to single spaces and strips
//     non-newline control characters.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <iostream>
#include <map>
#include <optional>
#include <random>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace fs = std::filesystem;

struct Args {
  std::string input;
  std::string output;
  std::string tokenizer = "byte";
  std::string vocab_path;
  std::string merges_path;
  long context_size = 2048;
  long eot_token = 0;
  long pad_token = 0;
  long boundary_token = -1;
  long boundary_overlap = 0;
  double sampling = 100.0;
  std::string reorder = "none";
  unsigned seed = 42;
  bool sanitize = false;
};

static void usage() {
  std::cerr <<
      "usage: dataset_tokenizer --input PATH --output OUT.tokens\n"
      "  [--tokenizer byte|bpe] [--vocab vocab.json] [--merges merges.txt]\n"
      "  [--context-size N] [--eot-token N] [--pad-token N]\n"
      "  [--boundary-token N] [--boundary-overlap N]\n"
      "  [--sampling PCT] [--reorder none|shuffle|reverse] [--seed N]\n"
      "  [--sanitize]\n";
}

static bool parse_args(int argc, char** argv, Args* out) {
  auto need = [&](int i) { return i + 1 < argc; };
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    // accept --dash-case and --underscore_case like the Python DashParser
    std::replace(a.begin(), a.end(), '_', '-');
    if (a == "--input" && need(i)) out->input = argv[++i];
    else if (a == "--output" && need(i)) out->output = argv[++i];
    else if (a == "--tokenizer" && need(i)) out->tokenizer = argv[++i];
    else if (a == "--vocab" && need(i)) out->vocab_path = argv[++i];
    else if (a == "--merges" && need(i)) out->merges_path = argv[++i];
    else if (a == "--context-size" && need(i)) out->context_size = atol(argv[++i]);
    else if (a == "--eot-token" && need(i)) out->eot_token = atol(argv[++i]);
    else if (a == "--pad-token" && need(i)) out->pad_token = atol(argv[++i]);
    else if (a == "--boundary-token" && need(i)) out->boundary_token = atol(argv[++i]);
    else if (a == "--boundary-overlap" && need(i)) out->boundary_overlap = atol(argv[++i]);
    else if (a == "--sampling" && need(i)) out->sampling = atof(argv[++i]);
    else if (a == "--reorder" && need(i)) out->reorder = argv[++i];
    else if (a == "--seed" && need(i)) out->seed = (unsigned)atol(argv[++i]);
    else if (a == "--sanitize") out->sanitize = true;
    else if (a == "--help" || a == "-h") { usage(); exit(0); }
    else { std::cerr << "unknown arg: " << a << "\n"; return false; }
  }
  if (out->input.empty() || out->output.empty()) { usage(); return false; }
  if (out->context_size <= 0 || out->context_size > 1 << 20) {
    std::cerr << "bad --context-size\n"; return false;
  }
  if (out->boundary_overlap < 0 ||
      out->boundary_overlap >= out->context_size) {
    std::cerr << "--boundary-overlap must be in [0, context-size)\n";
    return false;
  }
  return true;
}

// ---------------------------------------------------------------- tokenizers

// Minimal JSON parser for the vocab.json shape {"tok": 123, ...} with
// string escapes (incl. \uXXXX -> UTF-8).
static std::optional<std::unordered_map<std::string, int>>
load_vocab(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return std::nullopt;
  std::stringstream ss; ss << f.rdbuf();
  const std::string s = ss.str();
  std::unordered_map<std::string, int> vocab;
  size_t i = 0;
  auto skip_ws = [&] { while (i < s.size() && isspace((unsigned char)s[i])) ++i; };
  auto utf8_append = [](std::string* out, unsigned cp) {
    if (cp < 0x80) { out->push_back((char)cp); }
    else if (cp < 0x800) {
      out->push_back((char)(0xC0 | (cp >> 6)));
      out->push_back((char)(0x80 | (cp & 0x3F)));
    } else {
      out->push_back((char)(0xE0 | (cp >> 12)));
      out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out->push_back((char)(0x80 | (cp & 0x3F)));
    }
  };
  skip_ws();
  if (i >= s.size() || s[i] != '{') return std::nullopt;
  ++i;
  while (true) {
    skip_ws();
    if (i < s.size() && s[i] == '}') break;
    if (i >= s.size() || s[i] != '"') return std::nullopt;
    ++i;
    std::string key;
    while (i < s.size() && s[i] != '"') {
      if (s[i] == '\\' && i + 1 < s.size()) {
        char c = s[i + 1];
        if (c == 'u' && i + 5 < s.size()) {
          unsigned cp = (unsigned)strtoul(s.substr(i + 2, 4).c_str(), nullptr, 16);
          utf8_append(&key, cp);
          i += 6;
          continue;
        }
        i += 2;
        switch (c) {
          case 'n': key.push_back('\n'); break;
          case 't': key.push_back('\t'); break;
          case 'r': key.push_back('\r'); break;
          case 'b': key.push_back('\b'); break;
          case 'f': key.push_back('\f'); break;
          default: key.push_back(c);
        }
        continue;
      }
      key.push_back(s[i++]);
    }
    ++i;  // closing quote
    skip_ws();
    if (i >= s.size() || s[i] != ':') return std::nullopt;
    ++i;
    skip_ws();
    size_t end = i;
    while (end < s.size() && (isdigit((unsigned char)s[end]) || s[end] == '-')) ++end;
    vocab[key] = atoi(s.substr(i, end - i).c_str());
    i = end;
    skip_ws();
    if (i < s.size() && s[i] == ',') { ++i; continue; }
    if (i < s.size() && s[i] == '}') break;
  }
  return vocab;
}

// GPT-2's byte -> printable-unicode-char remapping (bytes_to_unicode).
static std::vector<std::string> byte_to_unicode_table() {
  std::vector<int> bs;
  for (int b = '!'; b <= '~'; ++b) bs.push_back(b);
  for (int b = 0xA1; b <= 0xAC; ++b) bs.push_back(b);
  for (int b = 0xAE; b <= 0xFF; ++b) bs.push_back(b);
  std::vector<int> cs = bs;
  int n = 0;
  for (int b = 0; b < 256; ++b) {
    if (std::find(bs.begin(), bs.end(), b) == bs.end()) {
      bs.push_back(b);
      cs.push_back(256 + n++);
    }
  }
  std::vector<std::string> table(256);
  for (size_t k = 0; k < bs.size(); ++k) {
    std::string u;
    unsigned cp = (unsigned)cs[k];
    if (cp < 0x80) u.push_back((char)cp);
    else if (cp < 0x800) {
      u.push_back((char)(0xC0 | (cp >> 6)));
      u.push_back((char)(0x80 | (cp & 0x3F)));
    }
    table[bs[k]] = u;
  }
  return table;
}

struct BPE {
  std::unordered_map<std::string, int> vocab;
  std::map<std::pair<std::string, std::string>, int> merge_rank;
  std::vector<std::string> byte_table = byte_to_unicode_table();
  std::unordered_map<std::string, std::vector<int>> cache;

  bool load(const std::string& vocab_path, const std::string& merges_path) {
    auto v = load_vocab(vocab_path);
    if (!v) return false;
    vocab = std::move(*v);
    std::ifstream mf(merges_path);
    if (!mf) return false;
    std::string line;
    int rank = 0;
    while (std::getline(mf, line)) {
      if (line.empty() || line[0] == '#') continue;
      auto sp = line.find(' ');
      if (sp == std::string::npos) continue;
      merge_rank[{line.substr(0, sp), line.substr(sp + 1)}] = rank++;
    }
    return true;
  }

  // Pre-tokenization approximating the GPT-2 pattern
  // ('s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|
  //  \s+(?!\S)|\s+) for byte-oriented text; non-ASCII bytes are treated
  // as letters (exact for ASCII corpora, see tests vs HF tokenizers).
  static std::vector<std::string> pretokenize(const std::string& text) {
    std::vector<std::string> words;
    size_t i = 0;
    const size_t n = text.size();
    auto is_letter = [](unsigned char c) { return isalpha(c) || c >= 0x80; };
    auto is_digit = [](unsigned char c) { return isdigit(c) != 0; };
    auto is_space = [](unsigned char c) { return isspace(c) != 0; };
    while (i < n) {
      if (text[i] == '\'') {
        static const char* conts[] = {"'re", "'ve", "'ll", "'s", "'t",
                                      "'m", "'d"};
        bool matched = false;
        for (const char* c : conts) {
          size_t len = strlen(c);
          if (text.compare(i, len, c) == 0) {
            words.push_back(text.substr(i, len));
            i += len;
            matched = true;
            break;
          }
        }
        if (matched) continue;
      }
      size_t j = i + (text[i] == ' ' ? 1 : 0);  // optional space prefix
      if (j < n && is_letter(text[j])) {
        size_t k = j;
        while (k < n && is_letter(text[k])) ++k;
        words.push_back(text.substr(i, k - i));
        i = k;
        continue;
      }
      if (j < n && is_digit(text[j])) {
        size_t k = j;
        while (k < n && is_digit(text[k])) ++k;
        words.push_back(text.substr(i, k - i));
        i = k;
        continue;
      }
      if (j < n && !is_space(text[j])) {
        size_t k = j;
        while (k < n && !is_space(text[k]) && !is_letter(text[k]) &&
               !is_digit(text[k]))
          ++k;
        words.push_back(text.substr(i, k - i));
        i = k;
        continue;
      }
      // whitespace run; a trailing single space attaches to the next word
      size_t k = i;
      while (k < n && is_space(text[k])) ++k;
      size_t end = (k < n && text[k - 1] == ' ') ? k - 1 : k;
      if (end > i) {
        words.push_back(text.substr(i, end - i));
        i = end;
      } else {
        ++i;  // lone space before a word: consumed as the prefix next loop
      }
    }
    return words;
  }

  std::vector<int> encode_word(const std::string& word) {
    auto it = cache.find(word);
    if (it != cache.end()) return it->second;
    // byte-remap then merge
    std::vector<std::string> parts;
    for (unsigned char c : word) parts.push_back(byte_table[c]);
    while (parts.size() > 1) {
      int best_rank = INT32_MAX;
      size_t best_i = 0;
      for (size_t k = 0; k + 1 < parts.size(); ++k) {
        auto r = merge_rank.find({parts[k], parts[k + 1]});
        if (r != merge_rank.end() && r->second < best_rank) {
          best_rank = r->second;
          best_i = k;
        }
      }
      if (best_rank == INT32_MAX) break;
      parts[best_i] = parts[best_i] + parts[best_i + 1];
      parts.erase(parts.begin() + best_i + 1);
    }
    std::vector<int> ids;
    for (auto& p : parts) {
      auto v = vocab.find(p);
      if (v != vocab.end()) ids.push_back(v->second);
      // unknown pieces are dropped (GPT-2 byte-level BPE has full coverage,
      // so this only happens with truncated vocab files)
    }
    cache[word] = ids;
    return ids;
  }

  std::vector<int> encode(const std::string& text) {
    std::vector<int> out;
    for (auto& w : pretokenize(text)) {
      auto ids = encode_word(w);
      out.insert(out.end(), ids.begin(), ids.end());
    }
    return out;
  }
};

// ------------------------------------------------------------------- helpers

static std::string sanitize_text(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  bool in_ws = false;
  for (unsigned char c : in) {
    if (c == '\n') { out.push_back('\n'); in_ws = false; continue; }
    if (isspace(c)) {
      if (!in_ws) out.push_back(' ');
      in_ws = true;
      continue;
    }
    if (c < 0x20) continue;  // strip control chars
    out.push_back((char)c);
    in_ws = false;
  }
  return out;
}

int main(int argc, char** argv) {
  Args args;
  if (!parse_args(argc, argv, &args)) return 2;

  // collect documents (sorted for determinism)
  std::vector<fs::path> files;
  fs::path in(args.input);
  if (fs::is_directory(in)) {
    for (auto& e : fs::recursive_directory_iterator(in))
      if (e.is_regular_file()) files.push_back(e.path());
    std::sort(files.begin(), files.end());
  } else if (fs::is_regular_file(in)) {
    files.push_back(in);
  } else {
    std::cerr << "no such input: " << args.input << "\n";
    return 2;
  }

  std::mt19937 rng(args.seed);
  if (args.reorder == "shuffle") std::shuffle(files.begin(), files.end(), rng);
  else if (args.reorder == "reverse") std::reverse(files.begin(), files.end());
  else if (args.reorder != "none") { std::cerr << "bad --reorder\n"; return 2; }

  BPE bpe;
  if (args.tokenizer == "bpe") {
    if (!bpe.load(args.vocab_path, args.merges_path)) {
      std::cerr << "failed to load vocab/merges\n";
      return 2;
    }
  } else if (args.tokenizer != "byte") {
    std::cerr << "bad --tokenizer\n";
    return 2;
  }

  std::uniform_real_distribution<double> unif(0.0, 100.0);
  const long C = args.context_size;
  std::vector<uint16_t> row;
  row.reserve(C);
  std::ofstream out(args.output + ".tmp", std::ios::binary);
  if (!out) { std::cerr << "cannot write " << args.output << "\n"; return 2; }
  long n_rows = 0, n_docs = 0, n_tokens = 0, max_id = 0;

  auto flush_row = [&](bool pad) {
    if (row.empty()) return;
    if (pad) while ((long)row.size() < C) row.push_back((uint16_t)args.pad_token);
    if ((long)row.size() == C) {
      out.write((const char*)row.data(), C * sizeof(uint16_t));
      ++n_rows;
      row.clear();
    }
  };

  for (auto& path : files) {
    if (args.sampling < 100.0 && unif(rng) >= args.sampling) continue;
    std::ifstream f(path, std::ios::binary);
    if (!f) continue;
    std::stringstream ss;
    ss << f.rdbuf();
    std::string text = ss.str();
    if (text.empty()) continue;
    if (args.sanitize) text = sanitize_text(text);

    std::vector<int> ids;
    if (args.tokenizer == "byte") {
      ids.reserve(text.size());
      for (unsigned char c : text) ids.push_back(c);
    } else {
      ids = bpe.encode(text);
    }
    ids.push_back((int)args.eot_token);
    ++n_docs;
    n_tokens += (long)ids.size();

    size_t i = 0;
    while (i < ids.size()) {
      long room = C - (long)row.size();
      long take = std::min<long>(room, (long)(ids.size() - i));
      for (long k = 0; k < take; ++k) {
        int id = ids[i + k];
        if (id > max_id) max_id = id;
        row.push_back((uint16_t)std::min(id, 0xFFFF));
      }
      i += take;
      if ((long)row.size() == C) {
        bool doc_continues = i < ids.size();
        if (doc_continues && args.boundary_token >= 0) {
          // cut at the document's last boundary token at index
          // >= boundary_overlap; resume after it
          long cut = -1;
          for (long k = C - 1; k >= args.boundary_overlap; --k) {
            if (row[k] == (uint16_t)args.boundary_token) { cut = k; break; }
          }
          if (cut >= 0 && cut + 1 < C) {
            long tail = C - (cut + 1);
            i -= tail;  // tokens after the boundary go to the next row
            row.resize(cut + 1);
            flush_row(/*pad=*/true);
            continue;
          }
        }
        flush_row(/*pad=*/false);
      }
    }
  }
  flush_row(/*pad=*/true);
  out.close();
  fs::rename(args.output + ".tmp", args.output);

  if (max_id > 0xFFFF) {
    std::cerr << "warning: token ids exceeded uint16 range and were "
                 "clamped; use a smaller vocab\n";
  }

  std::ofstream meta(args.output + ".json");
  meta << "{\"context_size\": " << C
       << ", \"rows\": " << n_rows
       << ", \"documents\": " << n_docs
       << ", \"tokens\": " << n_tokens
       << ", \"eot_token\": " << args.eot_token
       << ", \"pad_token\": " << args.pad_token
       << ", \"boundary_token\": " << args.boundary_token
       << ", \"boundary_overlap\": " << args.boundary_overlap
       << ", \"dtype\": \"uint16\"}\n";

  std::cout << "wrote " << n_rows << " contexts (" << n_tokens
            << " tokens from " << n_docs << " documents) to "
            << args.output << "\n";
  return 0;
}
