#!/usr/bin/env bash
# Workstation bootstrap for the TPU framework's GKE clusters.
#
# The reference ships a Windows PowerShell installer that fetches
# kubectl/virtctl/helm and merges the downloaded kubeconfig
# (getting-started/k8ctl_setup.ps1).  This is the equivalent for the
# GKE-TPU stack: kubectl + helm + the gke-gcloud-auth-plugin, plus
# kubeconfig merge for a named cluster.
#
# Usage:
#   ./setup.sh install                 # install missing tools to ~/.local/bin
#   ./setup.sh kubeconfig CLUSTER ZONE # merge GKE credentials
#   ./setup.sh verify                  # print tool + cluster status
set -euo pipefail

BIN_DIR="${BIN_DIR:-$HOME/.local/bin}"
KUBECTL_VERSION="${KUBECTL_VERSION:-stable}"
HELM_VERSION="${HELM_VERSION:-v3.15.2}"

say() { printf '>>> %s\n' "$*"; }

arch() {
  case "$(uname -m)" in
    x86_64) echo amd64 ;;
    aarch64 | arm64) echo arm64 ;;
    *) echo "unsupported arch $(uname -m)" >&2; exit 1 ;;
  esac
}

os() {
  case "$(uname -s)" in
    Linux) echo linux ;;
    Darwin) echo darwin ;;
    *) echo "unsupported OS $(uname -s)" >&2; exit 1 ;;
  esac
}

install_kubectl() {
  if command -v kubectl >/dev/null; then
    say "kubectl already installed: $(command -v kubectl)"
    return
  fi
  local ver="$KUBECTL_VERSION"
  if [ "$ver" = stable ]; then
    ver="$(curl -fsSL https://dl.k8s.io/release/stable.txt)"
  fi
  say "installing kubectl $ver -> $BIN_DIR"
  mkdir -p "$BIN_DIR"
  curl -fsSL "https://dl.k8s.io/release/${ver}/bin/$(os)/$(arch)/kubectl" \
    -o "$BIN_DIR/kubectl"
  chmod +x "$BIN_DIR/kubectl"
}

install_helm() {
  if command -v helm >/dev/null; then
    say "helm already installed: $(command -v helm)"
    return
  fi
  say "installing helm $HELM_VERSION -> $BIN_DIR"
  mkdir -p "$BIN_DIR"
  local tmp
  tmp="$(mktemp -d)"
  curl -fsSL \
    "https://get.helm.sh/helm-${HELM_VERSION}-$(os)-$(arch).tar.gz" |
    tar -xz -C "$tmp"
  mv "$tmp/$(os)-$(arch)/helm" "$BIN_DIR/helm"
  rm -rf "$tmp"
}

install_gke_auth_plugin() {
  if command -v gke-gcloud-auth-plugin >/dev/null; then
    say "gke-gcloud-auth-plugin already installed"
    return
  fi
  if command -v gcloud >/dev/null; then
    say "installing gke-gcloud-auth-plugin via gcloud components"
    gcloud components install gke-gcloud-auth-plugin --quiet
  else
    say "gcloud not found: install the Google Cloud SDK first" \
        "(https://cloud.google.com/sdk/docs/install)"
  fi
}

merge_kubeconfig() {
  local cluster="$1" zone="$2"
  command -v gcloud >/dev/null || {
    echo "gcloud required for kubeconfig merge" >&2; exit 1; }
  say "merging kubeconfig for cluster $cluster ($zone)"
  gcloud container clusters get-credentials "$cluster" --zone "$zone"
  kubectl config current-context
}

verify() {
  for tool in kubectl helm gke-gcloud-auth-plugin gcloud; do
    if command -v "$tool" >/dev/null; then
      say "$tool: $(command -v "$tool")"
    else
      say "$tool: MISSING"
    fi
  done
  if command -v kubectl >/dev/null && kubectl version --client >/dev/null 2>&1; then
    say "kubectl client: $(kubectl version --client 2>/dev/null | head -1)"
  fi
  if kubectl get nodes >/dev/null 2>&1; then
    say "cluster reachable; TPU nodepools:"
    kubectl get nodes \
      -L cloud.google.com/gke-tpu-accelerator,cloud.google.com/gke-tpu-topology \
      2>/dev/null | head -20
  else
    say "no reachable cluster context (run: $0 kubeconfig CLUSTER ZONE)"
  fi
}

case "${1:-}" in
  install)
    install_kubectl
    install_helm
    install_gke_auth_plugin
    say "done; ensure $BIN_DIR is on PATH"
    ;;
  kubeconfig)
    [ $# -eq 3 ] || { echo "usage: $0 kubeconfig CLUSTER ZONE" >&2; exit 1; }
    merge_kubeconfig "$2" "$3"
    ;;
  verify)
    verify
    ;;
  *)
    echo "usage: $0 {install|kubeconfig CLUSTER ZONE|verify}" >&2
    exit 1
    ;;
esac
