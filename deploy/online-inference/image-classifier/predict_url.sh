#!/bin/sh
# Smoke test: classify an image by URL (reference:
# image-classifier/service/predict_url.sh).
SERVICE=${SERVICE:-image-classifier.default.example.com}
URL=${1:-https://upload.wikimedia.org/wikipedia/commons/9/99/Brooks_Chase_Ranger_of_Jolly_Dogs_Jack_Russell.jpg}
curl -s -H "Content-Type: application/json" \
  "http://${SERVICE}/v1/models/classifier:predict" \
  -d "{\"instances\": [{\"image_url\": \"${URL}\"}]}"
echo
