#!/bin/sh
# Smoke test: classify a base64-encoded local image (reference:
# image-classifier/service/test_base64.sh).
SERVICE=${SERVICE:-image-classifier.default.example.com}
IMG=${1:?usage: test_base64.sh <image-file>}
B64=$(base64 -w0 "$IMG" 2>/dev/null || base64 "$IMG")
curl -s -H "Content-Type: application/json" \
  "http://${SERVICE}/v1/models/classifier:predict" \
  -d "{\"instances\": [{\"image_b64\": \"${B64}\"}]}"
echo
